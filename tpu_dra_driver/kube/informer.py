"""List+watch informers with local stores, indices and event handlers.

Reference analog: the generated informers/listers in pkg/nvidia.com/ plus
client-go SharedInformer semantics the driver relies on: initial sync
delivers ADDED for every existing object, then watch events stream; a
local thread-safe store answers lister queries without API round-trips;
named indexers (client-go ``cache.Indexers``) give O(1) grouped lookups
(e.g. daemon pods by ComputeDomain uid) that a poll loop would otherwise
pay a full LIST for on every tick.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from tpu_dra_driver.kube.client import ResourceClient
from tpu_dra_driver.kube.fake import (
    ADDED,
    DELETED,
    MODIFIED,
    RELIST,
    Object,
    deep_copy_obj,
)
from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg.metrics import (
    INFORMER_LISTER_HITS,
    INFORMER_WATCH_LAG,
    SWALLOWED_ERRORS,
)

fi.register("informer.resync",
            "one RELIST reconciliation pass (fail/latency models resync "
            "storms hammering a large store; the informer thread must "
            "survive and converge on the next resync)")

#: An indexer maps an object to the index values it appears under (zero or
#: more, client-go IndexFunc). Returning an empty iterable skips the object.
Indexer = Callable[[Object], Iterable[str]]

_Key = Tuple[str, str]  # (namespace, name)


class Informer:
    def __init__(self, client: ResourceClient,
                 namespace: Optional[str] = None,
                 label_selector: Optional[Dict[str, str]] = None,
                 name_filter: Optional[Callable[[str], bool]] = None,
                 indexers: Optional[Dict[str, Indexer]] = None,
                 object_filter: Optional[Callable[[Object], bool]] = None):
        self._client = client
        self._namespace = namespace
        self._selector = label_selector
        self._name_filter = name_filter
        # content-based accept predicate (e.g. a shard keeping only its
        # ring-owned pools' slices in store) — client-go gets this from
        # field selectors; the fake streams everything, so filter here
        self._object_filter = object_filter
        self._mu = threading.RLock()
        self._store: Dict[_Key, Object] = {}
        self._indexers: Dict[str, Indexer] = dict(indexers or {})
        # index name -> value -> set of store keys
        self._indices: Dict[str, Dict[str, set]] = {
            name: {} for name in self._indexers}
        self._handlers: List[Tuple[Optional[Callable], Optional[Callable], Optional[Callable]]] = []
        self._thread: Optional[threading.Thread] = None
        self._mux = None
        self._stop = threading.Event()
        self._sub = None
        self._synced = threading.Event()

    # -- handler registration ----------------------------------------------

    def add_handlers(self, on_add: Optional[Callable[[Object], None]] = None,
                     on_update: Optional[Callable[[Object, Object], None]] = None,
                     on_delete: Optional[Callable[[Object], None]] = None) -> None:
        # Registration, store replay, and event dispatch all serialize on
        # _mu so a late-registering handler cannot receive a duplicate ADDED
        # (once from replay, once from an in-flight dispatch).
        with self._mu:
            self._handlers.append((on_add, on_update, on_delete))
            if self._synced.is_set() and on_add:
                for obj in list(self._store.values()):
                    on_add(deep_copy_obj(obj))

    # -- lister -------------------------------------------------------------

    def get(self, name: str, namespace: str = "") -> Optional[Object]:
        with self._mu:
            self._count_lister_hit()
            obj = self._store.get((namespace or "", name))
            return deep_copy_obj(obj) if obj is not None else None

    def list(self, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Object]:
        """Store snapshot, optionally filtered. The signature matches
        :meth:`ResourceClient.list`'s keyword surface so an informer can
        stand in for the live client on read paths (e.g.
        ``multislice.live_cliques``)."""
        from tpu_dra_driver.kube.fake import match_label_selector
        with self._mu:
            self._count_lister_hit()
            out = []
            for (ns, _), obj in self._store.items():
                if namespace is not None and ns != namespace:
                    continue
                labels = (obj.get("metadata") or {}).get("labels") or {}
                if match_label_selector(labels, label_selector):
                    out.append(deep_copy_obj(obj))
            return out

    def by_index(self, index_name: str, value: str) -> List[Object]:
        """Objects whose indexer emitted ``value`` (client-go ByIndex)."""
        with self._mu:
            self._count_lister_hit()
            keys = self._indices[index_name].get(value) or ()
            return [deep_copy_obj(self._store[k]) for k in sorted(keys)]

    def index_values(self, index_name: str) -> List[str]:
        """All values currently present in the named index."""
        with self._mu:
            return sorted(self._indices[index_name])

    def _count_lister_hit(self) -> None:
        INFORMER_LISTER_HITS.labels(self._client.resource).inc()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        items, sub = self._client.list_and_watch(namespace=self._namespace,
                                                 label_selector=self._selector)
        self._sub = sub
        with self._mu:
            for obj in items:
                if self._accept(obj):
                    meta = obj["metadata"]
                    self._store_set(
                        (meta.get("namespace", ""), meta["name"]), obj)
            for obj in list(self._store.values()):
                self._dispatch(ADDED, obj, None)
            self._synced.set()
        # Event delivery: by default the shared watch mux services this
        # subscription from its fixed worker pool (N informers ≅ 4
        # threads, kube/aio.py); TPU_DRA_WATCH_MUX=0 restores the
        # historical thread-per-informer loop.
        from tpu_dra_driver.kube import aio
        if aio.mux_enabled():
            self._mux = aio.watch_mux()
            self._mux.add(sub, self._mux_dispatch)
        else:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"informer-{self._client.resource}")
            self._thread.start()

    def wait_synced(self, timeout: float = 5.0) -> bool:
        return self._synced.wait(timeout)

    @property
    def synced(self) -> bool:
        return self._synced.is_set()

    def stop(self) -> None:
        self._stop.set()
        if self._sub is not None:
            self._client.stop_watch(self._sub)
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self._mux is not None:
            self._mux.remove(self._sub, wait=True)
            self._mux = None

    # -- internals ----------------------------------------------------------

    def _accept(self, obj: Object) -> bool:
        meta = obj.get("metadata") or {}
        if self._namespace is not None and meta.get("namespace", "") != self._namespace:
            return False
        if self._name_filter is not None and not self._name_filter(meta.get("name", "")):
            return False
        if self._object_filter is not None and not self._object_filter(obj):
            return False
        return True

    def _store_set(self, key: _Key, obj: Object) -> None:
        """Call with _mu held: install obj and re-index it."""
        old = self._store.get(key)
        self._store[key] = obj
        for name, fn in self._indexers.items():
            index = self._indices[name]
            if old is not None:
                for v in fn(old) or ():
                    keys = index.get(v)
                    if keys is not None:
                        keys.discard(key)
                        if not keys:
                            del index[v]
            for v in fn(obj) or ():
                index.setdefault(v, set()).add(key)

    def _store_pop(self, key: _Key) -> Optional[Object]:
        """Call with _mu held: remove obj and de-index it."""
        old = self._store.pop(key, None)
        if old is not None:
            for name, fn in self._indexers.items():
                index = self._indices[name]
                for v in fn(old) or ():
                    keys = index.get(v)
                    if keys is not None:
                        keys.discard(key)
                        if not keys:
                            del index[v]
        return old

    def _next_event(self):
        """One event off the subscription, observing queue lag when the
        source exposes push timestamps (fake and REST subs both do)."""
        next_with_ts = getattr(self._sub, "next_with_ts", None)
        if next_with_ts is None:
            return self._sub.next(timeout=0.2)
        got = next_with_ts(timeout=0.2)
        if got is None:
            return None
        ev, pushed_at = got
        INFORMER_WATCH_LAG.labels(self._client.resource).observe(
            time.monotonic() - pushed_at)
        return ev

    def _run(self) -> None:
        while not self._stop.is_set():
            ev = self._next_event()
            if ev is None:
                if self._sub.closed:
                    return
                continue
            self._handle_event(ev)

    def _mux_dispatch(self, ev, pushed_at: float) -> None:
        """Mux-worker entry point: one event, same semantics as the
        dedicated-thread loop (the mux serializes per subscription, so
        the one-event-at-a-time invariant holds here too)."""
        if self._stop.is_set():
            return
        INFORMER_WATCH_LAG.labels(self._client.resource).observe(
            time.monotonic() - pushed_at)
        self._handle_event(ev)

    def _handle_event(self, ev) -> None:
        ev_type, obj = ev
        if ev_type == RELIST:
            # A failed resync must not kill the informer: the store
            # stays at its pre-gap state and the next RELIST (watch
            # layers relist again after every gap) converges.
            try:
                items = fi.fire("informer.resync",
                                payload=obj.get("items"))
                self._resync(items or [])
            except Exception:  # chaos-ok: counted; next RELIST heals
                SWALLOWED_ERRORS.labels("informer.resync").inc()
                import logging
                logging.getLogger(__name__).exception(
                    "informer resync failed (%s); awaiting next relist",
                    self._client.resource)
            return
        if not self._accept(obj):
            return
        meta = obj["metadata"]
        key = (meta.get("namespace", ""), meta["name"])
        # Store update + dispatch happen under one lock acquisition so
        # late handler registration (which replays the store under the
        # same lock) can't interleave and double-deliver.
        with self._mu:
            old = self._store.get(key)
            if ev_type == DELETED:
                self._store_pop(key)
            else:
                self._store_set(key, obj)
            self._dispatch(ev_type, obj, old)

    def _resync(self, items: List[Object]) -> None:
        """Reconcile the store against a fresh full list after a watch gap
        (client-go relist): emits ADDED for new objects, MODIFIED for
        changed resourceVersions, DELETED for objects gone from the list —
        so deletions that happened during the outage are not lost."""
        fresh: Dict[_Key, Object] = {}
        for obj in items:
            if self._accept(obj):
                meta = obj["metadata"]
                fresh[(meta.get("namespace", ""), meta["name"])] = obj
        with self._mu:
            for key, obj in fresh.items():
                old = self._store.get(key)
                self._store_set(key, obj)
                if old is None:
                    self._dispatch(ADDED, obj, None)
                elif ((old.get("metadata") or {}).get("resourceVersion")
                      != (obj.get("metadata") or {}).get("resourceVersion")):
                    self._dispatch(MODIFIED, obj, old)
            for key in [k for k in self._store if k not in fresh]:
                gone = self._store_pop(key)
                self._dispatch(DELETED, gone, None)

    def _dispatch(self, ev_type: str, obj: Object, old: Optional[Object]) -> None:
        """Call with _mu held. Hands each handler its own deep copy so
        handler mutations cannot corrupt the shared cache."""
        for on_add, on_update, on_delete in list(self._handlers):
            try:
                if ev_type == ADDED and on_add:
                    on_add(deep_copy_obj(obj))
                elif ev_type == MODIFIED:
                    if on_update:
                        on_update(deep_copy_obj(old) if old is not None
                                  else deep_copy_obj(obj), deep_copy_obj(obj))
                    elif on_add:
                        on_add(deep_copy_obj(obj))
                elif ev_type == DELETED and on_delete:
                    on_delete(deep_copy_obj(obj))
            except Exception:  # chaos-ok: handler errors must not kill the informer
                SWALLOWED_ERRORS.labels("informer.handler").inc()
                import logging
                logging.getLogger(__name__).exception(
                    "informer handler error (%s %s)", ev_type,
                    obj.get("metadata", {}).get("name"))
