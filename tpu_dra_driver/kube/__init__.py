"""kube — self-contained Kubernetes client machinery.

Reference analog: client-go + the generated CRD clientset/informers/listers
(pkg/nvidia.com/{clientset,informers,listers}). The reference vendors
client-go; this build implements the same *protocol surface* the driver
needs from scratch:

- :mod:`fake`     — an in-memory API server with resourceVersion bookkeeping,
  label-selector list/watch, optimistic-concurrency updates, and
  finalizer-aware deletion (the fake clientset test seam the reference has
  but barely uses, here the primary CI substrate).
- :mod:`client`   — typed per-resource clients over an abstract store, so
  components are written against the interface and can later bind to a real
  API server via HTTPS without change.
- :mod:`informer` — list+watch informers with local stores (listers) and
  add/update/delete handlers.
- :mod:`leaderelection` — lease-based leader election for the controller.
"""

from tpu_dra_driver.kube.errors import (  # noqa: F401
    ApiError,
    ConflictError,
    AlreadyExistsError,
    GoneError,
    NotFoundError,
)
from tpu_dra_driver.kube.fake import FakeCluster  # noqa: F401
from tpu_dra_driver.kube.client import ResourceClient, ClientSets  # noqa: F401
from tpu_dra_driver.kube.informer import Informer  # noqa: F401
