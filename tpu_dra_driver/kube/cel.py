"""A small recursive-descent CEL compiler+evaluator for DRA selectors.

The real scheduler evaluates full CEL against each device
(k8s.io/dynamic-resource-allocation/cel); the in-process allocator (the
scheduler stand-in for tests, demos, and the sim e2e suite) needs to
honor the same selectors that ship in `deviceclasses.yaml` and the
controller's claim templates — plus the shapes users realistically
write: `||`, `!`, parentheses, `in` over list literals.

Compilation is split from evaluation (the KEP-4381 scheduler-side hot
loop evaluates one selector against every candidate device):
``compile_selector(expr)`` tokenizes+parses once into a closure tree
behind a bounded LRU cache keyed by expression text — compile-time
errors (syntax, int64 literal overflow, non-RE2 literal patterns,
macro-variable validation, method arity) are cached *as* errors and
re-raise identically on every hit — and
``CompiledSelector.evaluate(resolver)`` walks the compiled form with a
per-device resolver, preserving the one-pass evaluator's value-dependent
error surface (missing propagation, type errors, division by zero)
message-for-message. ``evaluate(expr, resolver)`` composes the two.

Supported grammar (fail-loud `CelUnsupportedError` on anything else, so
a selector the allocator cannot faithfully evaluate never silently
matches or mismatches):

    expr   := or
    or     := and ( "||" and )*
    and    := cmp ( "&&" cmp )*
    cmp    := sum ( ("=="|"!="|">="|"<="|">"|"<") sum
                   | "in" list )?
    sum    := term ( ("+"|"-") term )*
    term   := uop ( ("*"|"/"|"%") uop )*
    uop    := "!" uop | "-" uop
            | operand ( "." ident "(" args ")"
                      | "." ("exists"|"all") "(" ident "," expr ")" )*
    operand:= literal | path | list | macro-var
            | "quantity" "(" string ")" | "size" "(" expr ")"
            | "has" "(" path ")" | "(" expr ")"
    path   := "device" "." "driver"
            | "device" "." ("attributes"|"capacity") "[" string "]"
              "." ident
    list   := "[" ( ("-"? int | string | bool) ( "," ... )* )? "]"
    literal:= string | int | "true" | "false"

Arithmetic follows the CEL/Go int64 semantics: `/` truncates toward
zero, `%` takes the dividend's sign (both differ from Python's floor
behavior on negatives), division by zero is a runtime error
(propagates like a missing value), and `+` also concatenates two
strings. The `exists`/`all` comprehension macros run over list
literals with CEL's OR/AND error-absorption aggregation; `size()`
(global and method form) covers strings and lists; `has(path)` is
the cel-spec presence macro — the one construct where a missing
attribute yields false instead of an error.

``!`` binds tighter than comparisons (CEL precedence: ``!a == b`` is
``(!a) == b``); parenthesize to negate a comparison.

String functions (the cel-spec standard surface real DeviceClass
selectors use — reference deviceclass-gpu.yaml:10-11):
``.startsWith(s)``, ``.endsWith(s)``, ``.contains(s)``, ``.matches(re)``.
``matches`` is an unanchored partial match; patterns using
backreferences, lookaround, atomic/conditional groups, or possessive
quantifiers are rejected fail-loud (RE2, the real CEL regex engine, has
no such constructs — evaluating them here would silently diverge from
the scheduler), and a pattern that does not compile here is likewise
fail-loud (the RE2 verdict cannot be mirrored without RE2). Ordered
operators cover int/int and string/string (lexicographic), per the CEL
standard definitions.

Quantities (the k8s CEL quantity library, apiserver
pkg/cel/library/quantity.go): ``quantity("16Gi")`` constructs one;
``device.capacity[...]`` values that are quantity STRINGS resolve to
one (plain ints stay ints). Methods: ``.compareTo(q)``,
``.isGreaterThan(q)``, ``.isLessThan(q)``, ``.sign()``,
``.asInteger()``, ``.isInteger()``. Ordered OPERATORS on quantities
are deliberately unsupported (the real CEL environment has no such
overloads — a selector must not match in-process and then type-error
on the real scheduler); use ``.compareTo``/``.isGreaterThan``.

Equality is heterogeneous the way modern CEL's is: values of different
types (bool vs int vs string vs quantity) compare unequal instead of
borrowing Python's ``True == 1``; quantity==quantity compares
numerically ("1Gi" equals "1024Mi").

Semantics follow the scheduler where the driver depends on them:
attribute domains resolve within the publishing driver's domain; a
qualified domain that is not the device's driver yields a *missing*
value. Missing propagates the way a CEL runtime error does: through
comparisons (including ``!=``), ``in``, ``!``, and method calls; it is
absorbed by ``&&`` when the other side is false and by ``||`` when the
other side is true (CEL's commutative short-circuit); a missing
overall result means the device does not match.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from fractions import Fraction
from typing import Any, Callable, List, NamedTuple, Optional

from tpu_dra_driver.pkg import metrics as _metrics

# Sentinel for "attribute absent" — the public name is the resolver
# contract (allocator.py returns it); it behaves like a CEL runtime
# error during evaluation.
MISSING = object()
_MISSING = MISSING

# Sentinel for "the DOMAIN map key itself is absent" (a qualified domain
# that is not the device's driver). Everywhere it behaves exactly like
# MISSING — except under has(): per cel-spec, has() absorbs absence of
# the FINAL field only, while an error from indexing the domain map
# still propagates. Collapsing the two would let `!has(...)` silently
# match where the real scheduler errors.
MISSING_DOMAIN = object()


class CelUnsupportedError(ValueError):
    """The expression uses CEL the in-process allocator does not speak."""


class CelEvalError(ValueError):
    """The expression parsed but evaluated to something non-boolean."""


_QTY_SUFFIX = {
    "": 1, "n": Fraction(1, 10**9), "u": Fraction(1, 10**6),
    "m": Fraction(1, 1000),
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15,
    "E": 10**18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
    "Ei": 2**60,
}

_QTY_RE = re.compile(
    r"^([+-]?)(\d+(?:\.\d*)?|\.\d+)"
    r"(?:([eE][+-]?\d+)|(Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]))?$")


class Quantity:
    """k8s resource.Quantity: exact decimal/binary-suffixed number.

    Parsed per apimachinery's grammar (sign, decimal digits, then one of
    an e-exponent or a binary/decimal SI suffix); held as an exact
    Fraction so "1Gi" == "1024Mi" and comparisons never round. Only the
    operations the k8s CEL quantity library exposes are offered (see
    module docstring)."""

    __slots__ = ("value", "text")

    def __init__(self, text: str):
        if isinstance(text, Quantity):
            self.value, self.text = text.value, text.text
            return
        m = _QTY_RE.match(str(text).strip())
        if not m:
            raise CelEvalError(f"invalid quantity {text!r}")
        sign, digits, exp, suffix = m.groups()
        val = Fraction(digits)
        if exp:
            val *= Fraction(10) ** int(exp[1:])
        if suffix:
            val *= _QTY_SUFFIX[suffix]
        if sign == "-":
            val = -val
        self.value = val
        self.text = str(text).strip()

    # -- the k8s CEL quantity library surface -----------------------------
    def compareTo(self, other: "Quantity") -> int:  # noqa: N802
        o = _require_quantity(other, "compareTo")
        return (self.value > o.value) - (self.value < o.value)

    def isGreaterThan(self, other: "Quantity") -> bool:  # noqa: N802
        return self.value > _require_quantity(other, "isGreaterThan").value

    def isLessThan(self, other: "Quantity") -> bool:  # noqa: N802
        return self.value < _require_quantity(other, "isLessThan").value

    def sign(self) -> int:
        return (self.value > 0) - (self.value < 0)

    def isInteger(self) -> bool:  # noqa: N802
        return self.value.denominator == 1

    def asInteger(self) -> int:  # noqa: N802
        if self.value.denominator != 1:
            raise CelEvalError(f"quantity {self.text!r} is not an integer")
        return self.value.numerator

    def __repr__(self) -> str:
        return f"quantity({self.text!r})"


def _require_quantity(v: Any, method: str) -> Quantity:
    if isinstance(v, Quantity):
        return v
    raise CelUnsupportedError(
        f"{method}() takes a quantity argument (use quantity(\"...\")), "
        f"got {v!r}")


#: methods callable on a Quantity from a selector, with arity
_QTY_METHODS = {"compareTo": 1, "isGreaterThan": 1, "isLessThan": 1,
                "sign": 0, "isInteger": 0, "asInteger": 0}

#: CEL string functions (cel-spec standard definitions; the surface real
#: DeviceClass selectors use, reference deviceclass-gpu.yaml:10-11)
_STR_METHODS = {"startsWith": 1, "endsWith": 1, "contains": 1,
                "matches": 1}

# Python-re constructs RE2 (CEL's regex engine) rejects: lookaround and
# atomic/conditional groups `(?=` `(?!` `(?<` `(?>` `(?(`, named and
# numeric backreferences `(?P=` `\1`, and possessive quantifiers `a*+`.
# A pattern using them would EVALUATE here but runtime-error on the real
# scheduler — the silent-divergence case the fail-loud boundary exists
# to prevent. Best-effort textual guard ((?P<name>...> groups are fine —
# both engines take them); the re.error path below fail-louds the rest.
_NON_RE2_RE = re.compile(r"\(\?[=!<>(]|\(\?P=|\\[1-9]"
                         r"|(?<!\\)(?:[*+?]|\})\+")


def _cel_size(v: Any) -> Any:
    """CEL's size(): string length (unicode code points) or list
    length. Errors (missing) propagate; other types are real-scheduler
    type errors, fail-loud."""
    if v is _MISSING:
        return _MISSING
    if isinstance(v, (str, list)):
        return len(v)
    raise CelUnsupportedError(
        f"size() takes a string or list, got {v!r}")


def _cel_matches(s: str, pattern: str) -> Any:
    """Dynamic-pattern matches(): validates through the same
    ``_check_re2_pattern`` the compiler uses for literal patterns, so
    the two paths can never drift apart in messages or verdicts."""
    # CEL matches() is an UNANCHORED partial match (re.search semantics)
    return _check_re2_pattern(pattern).search(s) is not None


def _type_tag(v: Any) -> str:
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, Quantity):
        return "quantity"
    if isinstance(v, int):
        return "int"
    return type(v).__name__


def _hetero_eq(lhs: Any, rhs: Any) -> bool:
    """Modern-CEL heterogeneous equality: cross-type is unequal (never
    Python's True == 1); quantities compare numerically."""
    if _type_tag(lhs) != _type_tag(rhs):
        return False
    if isinstance(lhs, Quantity):
        return lhs.value == rhs.value
    return lhs == rhs


_INT64_MIN, _INT64_MAX = -(2 ** 63), 2 ** 63 - 1


def _int64_or_error(v: int) -> Any:
    """CEL ints are int64 and overflow is a RUNTIME error in cel-go;
    Python's unbounded ints would silently succeed where the real
    scheduler errors — return missing (runtime-error semantics) so the
    two never diverge on a match."""
    return v if _INT64_MIN <= v <= _INT64_MAX else _MISSING


class _Tok(NamedTuple):
    kind: str     # op | ident | str | int
    value: Any


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<op>\|\||&&|==|!=|>=|<=|[!><()\[\],.+\-*/%])
    | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
    | (?P<int>\d+)
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    )""", re.X)


def _tokenize(src: str) -> List[_Tok]:
    toks: List[_Tok] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m or m.end() == pos:
            rest = src[pos:].strip()
            if not rest:
                break
            raise CelUnsupportedError(f"unsupported CEL at {rest[:40]!r}")
        pos = m.end()
        if m.group("op"):
            toks.append(_Tok("op", m.group("op")))
        elif m.group("str") is not None:
            raw = m.group("str")
            body = raw[1:-1]
            body = re.sub(r"\\(.)", r"\1", body)
            toks.append(_Tok("str", body))
        elif m.group("int") is not None:
            toks.append(_Tok("int", int(m.group("int"))))
        else:
            toks.append(_Tok("ident", m.group("ident")))
    return toks


# resolver(section, domain, name) -> value or _MISSING.
# section: "driver" (domain/name empty), "attributes", "capacity".
Resolver = Callable[[str, str, str], Any]


class _Env:
    """Per-evaluation state threaded through the compiled closure tree:
    the device resolver plus macro-variable bindings. One fresh instance
    per ``CompiledSelector.evaluate`` call, so a compiled selector is
    safe to share across threads and devices."""

    __slots__ = ("resolve", "locals")

    def __init__(self, resolve: Resolver):
        self.resolve = resolve
        self.locals: dict = {}


def _const(value: Any):
    """A constant node. The ``const``/``value`` attributes let the
    compiler see through it (literal-pattern precompilation for
    ``matches()``, static ``in`` lists)."""
    def node(env: _Env, _v=value) -> Any:
        return _v
    node.const = True
    node.value = value
    return node


def _boolish(val: Any) -> Any:
    """True / False / _MISSING; anything else is a type error."""
    if val is _MISSING or isinstance(val, bool):
        return val
    raise CelEvalError(f"expected boolean, got {val!r}")


def _compare(op: str, lhs: Any, rhs: Any) -> Any:
    if lhs is _MISSING or rhs is _MISSING:
        # a CEL runtime error (missing map key) propagates through
        # every comparison, != included
        return _MISSING
    if op == "==":
        return _hetero_eq(lhs, rhs)
    if op == "!=":
        return not _hetero_eq(lhs, rhs)
    if isinstance(lhs, Quantity) or isinstance(rhs, Quantity):
        # the real CEL environment has no ordered-operator overloads
        # for quantity — matching here and type-erroring on the real
        # scheduler would be the worst outcome
        raise CelUnsupportedError(
            f"ordered operators are not defined on quantities "
            f"({lhs!r} {op} {rhs!r}); use "
            f".compareTo(quantity(\"...\")) or .isGreaterThan(...)")
    int_pair = (isinstance(lhs, int) and not isinstance(lhs, bool)
                and isinstance(rhs, int) and not isinstance(rhs, bool))
    str_pair = isinstance(lhs, str) and isinstance(rhs, str)
    if not (int_pair or str_pair):
        # CEL defines < <= > >= on int/int and string/string
        # (lexicographic); a mixed pair is a real-scheduler type error
        raise CelUnsupportedError(
            f"ordered comparison needs two ints or two strings, "
            f"got {lhs!r} {op} {rhs!r}")
    return {"<": lhs < rhs, "<=": lhs <= rhs,
            ">": lhs > rhs, ">=": lhs >= rhs}[op]


def _arith(op: str, lhs: Any, rhs: Any) -> Any:
    if lhs is _MISSING or rhs is _MISSING:
        return _MISSING
    if op == "+" and isinstance(lhs, str) and isinstance(rhs, str):
        return lhs + rhs
    int_pair = (isinstance(lhs, int) and not isinstance(lhs, bool)
                and isinstance(rhs, int) and not isinstance(rhs, bool))
    if not int_pair:
        # the k8s CEL environment defines arithmetic on int/int
        # (and + on string/string); anything else is a type error
        raise CelUnsupportedError(
            f"arithmetic needs two ints (or + on two strings), "
            f"got {lhs!r} {op} {rhs!r}")
    if op == "+":
        return _int64_or_error(lhs + rhs)
    if op == "-":
        return _int64_or_error(lhs - rhs)
    if op == "*":
        return _int64_or_error(lhs * rhs)
    if rhs == 0:
        return _MISSING      # CEL runtime error: division by zero
    # CEL (Go) semantics: division truncates toward zero and the
    # modulo's sign follows the dividend — Python's floor division
    # differs on negatives
    q = abs(lhs) // abs(rhs)
    if (lhs < 0) != (rhs < 0):
        q = -q
    # -2^63 / -1 overflows int64 (the one division overflow)
    return _int64_or_error(q if op == "/" else lhs - q * rhs)


def _call_method_value(val: Any, method: str, args: List[Any]) -> Any:
    """Dynamic (value-dependent) half of a method call. Method existence
    and arity were already validated at compile time; what remains is
    exactly the checks whose outcome depends on per-device values —
    their order (missing-propagation BEFORE receiver/argument type
    checks) is the one-pass evaluator's, preserved bit-for-bit."""
    if method == "size":               # receiver form: x.size()
        return _cel_size(val)
    if val is _MISSING or any(a is _MISSING for a in args):
        return _MISSING
    if method in _STR_METHODS:
        if not isinstance(val, str):
            raise CelUnsupportedError(
                f".{method}() is a string method; receiver is {val!r}")
        if not isinstance(args[0], str):
            raise CelUnsupportedError(
                f".{method}() takes a string argument, got {args[0]!r}")
        if method == "startsWith":
            return val.startswith(args[0])
        if method == "endsWith":
            return val.endswith(args[0])
        if method == "contains":
            return args[0] in val
        return _cel_matches(val, args[0])
    if not isinstance(val, Quantity):
        raise CelUnsupportedError(
            f".{method}() is a quantity method; receiver is {val!r}")
    return getattr(val, method)(*args)


class _Compiler:
    """Recursive-descent compiler: tokens -> a closure tree.

    The grammar and error surface are the former one-pass evaluator's,
    split along the compile/evaluate seam: anything value-INDEPENDENT
    (syntax, int64 literal overflow, quantity() literal parsing, macro
    variable validation, method existence/arity, literal regex patterns)
    raises here at compile time, so a bad expression costs one cached
    error instead of one error per device; anything value-DEPENDENT
    (missing propagation, receiver/operand type errors, division by
    zero, arithmetic overflow) lives inside the returned closures and
    still surfaces per device with identical messages.

    ``scope`` is the compile-time set of macro-bound variable names; at
    evaluation time the bindings live in ``_Env.locals``.
    """

    def __init__(self, toks: List[_Tok]):
        self.toks = toks
        self.i = 0
        self.scope: set = set()   # macro-bound variables (exists/all)

    def peek(self) -> Optional[_Tok]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> _Tok:
        tok = self.peek()
        if tok is None:
            raise CelUnsupportedError("unexpected end of expression")
        self.i += 1
        return tok

    def expect_op(self, op: str) -> None:
        tok = self.next()
        if tok.kind != "op" or tok.value != op:
            raise CelUnsupportedError(f"expected {op!r}, got {tok.value!r}")

    # -- grammar -----------------------------------------------------------

    def compile(self):
        fn = self.or_expr()
        if self.peek() is not None:
            raise CelUnsupportedError(
                f"trailing tokens from {self.peek().value!r}")
        return fn

    def or_expr(self):
        fn = self.and_expr()
        while self._at_op("||"):
            self.next()
            rhs = self.and_expr()
            lhs = fn

            # CEL's commutative ||: true absorbs an error on either
            # side. Both sides evaluate (the one-pass evaluator had no
            # short-circuit either — a type error on the right must
            # surface even when the left is true).
            def node(env: _Env, _l=lhs, _r=rhs) -> Any:
                a, b = _boolish(_l(env)), _boolish(_r(env))
                if a is True or b is True:
                    return True
                if a is _MISSING or b is _MISSING:
                    return _MISSING
                return False
            fn = node
        return fn

    def and_expr(self):
        fn = self.cmp()
        while self._at_op("&&"):
            self.next()
            rhs = self.cmp()
            lhs = fn

            # CEL's commutative &&: false absorbs an error on either side
            def node(env: _Env, _l=lhs, _r=rhs) -> Any:
                a, b = _boolish(_l(env)), _boolish(_r(env))
                if a is False or b is False:
                    return False
                if a is _MISSING or b is _MISSING:
                    return _MISSING
                return True
            # pre-analysis seam: the index-probe planner flattens the
            # conjunction tree through this attribute
            node.conjuncts = (lhs, rhs)
            fn = node
        return fn

    def cmp(self):
        # ``!`` lives INSIDE the comparison operands (CEL precedence:
        # ``!a == b`` is ``(!a) == b``, not ``!(a == b)``)
        lhs = self.sum()
        tok = self.peek()
        if tok is None:
            return lhs
        if tok.kind == "op" and tok.value in ("==", "!=", ">", "<", ">=", "<="):
            op = self.next().value
            rhs = self.sum()

            def node(env: _Env, _op=op, _l=lhs, _r=rhs) -> Any:
                return _compare(_op, _l(env), _r(env))
            if op == "==":
                # pre-analysis seam: equality over a device path and a
                # constant is an index-probe candidate
                node.eq_operands = (lhs, rhs)
            return node
        if tok.kind == "ident" and tok.value == "in":
            self.next()
            items = self.list_literal()      # static: literals only

            def node(env: _Env, _l=lhs, _items=items) -> Any:
                v = _l(env)
                if v is _MISSING:
                    return _MISSING
                return any(_hetero_eq(v, item) for item in _items)
            return node
        return lhs

    def sum(self):
        """Additive arithmetic: int+int / int-int, and CEL's string
        concatenation for +. Binds tighter than comparisons, looser
        than * / %."""
        fn = self.term()
        while self._at_op("+") or self._at_op("-"):
            op = self.next().value
            rhs = self.term()
            fn = self._arith_node(op, fn, rhs)
        return fn

    def term(self):
        fn = self.unary_operand()
        while self._at_op("*") or self._at_op("/") or self._at_op("%"):
            op = self.next().value
            rhs = self.unary_operand()
            fn = self._arith_node(op, fn, rhs)
        return fn

    @staticmethod
    def _arith_node(op: str, lhs, rhs):
        def node(env: _Env, _op=op, _l=lhs, _r=rhs) -> Any:
            return _arith(_op, _l(env), _r(env))
        return node

    def unary_operand(self):
        if self._at_op("!"):
            self.next()
            inner = self.unary_operand()

            def node(env: _Env, _i=inner) -> Any:
                val = _boolish(_i(env))
                return _MISSING if val is _MISSING else not val
            return node
        if self._at_op("-"):
            self.next()
            # cel-go folds the minus into an int literal, which is how
            # INT64_MIN (whose magnitude alone exceeds INT64_MAX) is
            # written; fold here too before the literal-overflow check
            nxt = self.peek()
            if (nxt is not None and nxt.kind == "int"
                    and nxt.value == -_INT64_MIN):
                self.next()
                return _const(_INT64_MIN)
            inner = self.unary_operand()

            def node(env: _Env, _i=inner) -> Any:
                val = _i(env)
                if val is _MISSING:
                    return _MISSING
                if not isinstance(val, int) or isinstance(val, bool):
                    raise CelUnsupportedError(f"unary - needs an int, "
                                              f"got {val!r}")
                return _int64_or_error(-val)
            return node
        return self.postfix()

    def postfix(self):
        """An operand with any trailing ``.method(args)`` calls (the
        quantity/string library surfaces) or ``.exists(v, p)`` /
        ``.all(v, p)`` macros."""
        fn = self.operand()
        while (self._at_op(".")
               and self.i + 1 < len(self.toks)
               and self.toks[self.i + 1].kind == "ident"
               and self.i + 2 < len(self.toks)
               and self.toks[self.i + 2] == _Tok("op", "(")):
            self.next()                      # .
            method = self.next().value       # ident
            self.expect_op("(")
            if method in ("exists", "all"):
                fn = self._macro(method, fn)
                self.expect_op(")")
                continue
            args: List[Any] = []
            if not self._at_op(")"):
                args.append(self.or_expr())
                while self._at_op(","):
                    self.next()
                    args.append(self.or_expr())
            self.expect_op(")")
            fn = self._method_node(fn, method, args)
        return fn

    def _method_node(self, recv, method: str, args: List[Any]):
        # method existence and arity are value-independent: compile
        # errors now (identical messages), cached as errors
        if method == "size":               # receiver form: x.size()
            if args:
                raise CelUnsupportedError(".size() takes no arguments")
        else:
            arity = _QTY_METHODS.get(method, _STR_METHODS.get(method))
            if arity is None:
                raise CelUnsupportedError(f"unsupported method .{method}()")
            if len(args) != arity:
                raise CelUnsupportedError(
                    f".{method}() takes {arity} argument(s), got {len(args)}")
        if (method == "matches" and getattr(args[0], "const", False)
                and isinstance(args[0].value, str)):
            # literal pattern: validate + precompile ONCE at compile
            # time (a non-RE2 or non-compiling pattern is a cached
            # compile error, not one error per device) — the compiled
            # regex is also the per-device evaluation fast path
            compiled_re = _check_re2_pattern(args[0].value)

            def node(env: _Env, _recv=recv, _re=compiled_re) -> Any:
                val = _recv(env)
                if val is _MISSING:
                    return _MISSING
                if not isinstance(val, str):
                    raise CelUnsupportedError(
                        f".matches() is a string method; receiver is {val!r}")
                # CEL matches() is an UNANCHORED partial match
                return _re.search(val) is not None
            return node

        def node(env: _Env, _recv=recv, _method=method, _args=args) -> Any:
            return _call_method_value(
                _recv(env), _method, [a(env) for a in _args])
        return node

    def _macro(self, name: str, recv):
        """CEL comprehension macros over list literals: the predicate is
        compiled ONCE with the variable in compile scope; evaluation
        binds each element into ``env.locals`` and re-walks the compiled
        predicate (the former one-pass evaluator re-PARSED the token
        span per element). CEL aggregation semantics: ``exists`` =
        logical OR with error absorption (any true wins, else error if
        any erred), ``all`` = the dual."""
        var = self.next()
        if var.kind != "ident":
            raise CelUnsupportedError(
                f".{name}() takes a variable name, got {var.value!r}")
        if var.value in self.scope:
            raise CelUnsupportedError(
                f".{name}() variable {var.value!r} shadows an outer "
                f"macro variable")
        if var.value in ("device", "quantity", "size", "has", "true",
                         "false", "in"):
            raise CelUnsupportedError(
                f".{name}() variable {var.value!r} shadows a reserved name")
        self.expect_op(",")
        varname = var.value
        self.scope.add(varname)
        try:
            pred = self.or_expr()
        finally:
            self.scope.discard(varname)

        def node(env: _Env, _recv=recv, _name=name, _var=varname,
                 _pred=pred) -> Any:
            receiver = _recv(env)
            if not isinstance(receiver, list):
                raise CelUnsupportedError(
                    f".{_name}() macro needs a list receiver, "
                    f"got {receiver!r}")
            results: List[Any] = []
            # empty list: the predicate still evaluates once (matching
            # the one-pass evaluator, which had to consume its tokens;
            # a MISSING binding keeps evaluation inert) so its
            # value-independent type errors surface identically
            for elem in (receiver or [_MISSING]):
                env.locals[_var] = elem
                try:
                    results.append(_boolish(_pred(env)))
                finally:
                    del env.locals[_var]
            if not receiver:
                return _name == "all"
            if _name == "exists":
                if any(r is True for r in results):
                    return True
                return (_MISSING if any(r is _MISSING for r in results)
                        else False)
            if any(r is False for r in results):
                return False
            return _MISSING if any(r is _MISSING for r in results) else True
        return node

    def operand(self):
        tok = self.peek()
        if tok is None:
            raise CelUnsupportedError("unexpected end of expression")
        if tok.kind == "op" and tok.value == "(":
            self.next()
            fn = self.or_expr()
            self.expect_op(")")
            return fn
        if tok.kind == "op" and tok.value == "[":
            return _const(self.list_literal())   # a list operand (macros)
        if tok.kind in ("str", "int"):
            if tok.kind == "int" and tok.value > _INT64_MAX:
                # int literal overflow is a COMPILE error in cel-go
                raise CelUnsupportedError(
                    f"int literal {tok.value} exceeds int64")
            return _const(self.next().value)
        if tok.kind == "ident":
            if tok.value == "true":
                self.next()
                return _const(True)
            if tok.value == "false":
                self.next()
                return _const(False)
            if tok.value == "device":
                return self.device_path()
            if tok.value in self.scope:
                self.next()

                def node(env: _Env, _n=tok.value) -> Any:
                    return env.locals[_n]
                return node
            if tok.value == "quantity":
                self.next()
                self.expect_op("(")
                arg = self.next()
                if arg.kind != "str":
                    raise CelUnsupportedError(
                        f"quantity() takes a string literal, got "
                        f"{arg.value!r}")
                self.expect_op(")")
                # literal argument: parse at compile time, so an invalid
                # quantity is a cached compile error (same message the
                # one-pass evaluator raised mid-parse)
                return _const(Quantity(arg.value))
            if tok.value == "size":
                self.next()
                self.expect_op("(")
                arg = self.or_expr()
                self.expect_op(")")

                def node(env: _Env, _a=arg) -> Any:
                    return _cel_size(_a(env))
                return node
            if tok.value == "has":
                # the cel-spec presence macro: has(device.attributes[d].a)
                # is the ONE construct where a missing FINAL field yields
                # false instead of an error — the guard idiom selectors
                # use. Absence of the domain map key itself is still an
                # error (cel-spec: has() wraps the final select only; the
                # inner index evaluates first and its error propagates).
                self.next()
                self.expect_op("(")
                tok2 = self.peek()
                if not (tok2 is not None and tok2.kind == "ident"
                        and tok2.value == "device"):
                    raise CelUnsupportedError(
                        "has() takes a device.attributes/capacity path")
                path = self.device_path(raw=True)
                self.expect_op(")")

                def node(env: _Env, _p=path) -> Any:
                    val = _p(env)
                    if val is MISSING_DOMAIN:
                        return _MISSING
                    return val is not _MISSING
                return node
            raise CelUnsupportedError(f"unsupported identifier {tok.value!r}")
        raise CelUnsupportedError(f"unsupported token {tok.value!r}")

    def device_path(self, raw: bool = False):
        """``raw=True`` (the has() macro) preserves the MISSING_DOMAIN
        sentinel; normal evaluation collapses it to missing — the two
        only differ under has()."""
        self.next()              # device
        self.expect_op(".")
        field = self.next()
        if field.kind != "ident":
            raise CelUnsupportedError(f"expected field after device., got "
                                      f"{field.value!r}")
        if field.value == "driver":
            def node(env: _Env) -> Any:
                return env.resolve("driver", "", "")
            node.device_path = ("driver", "", "")
            return node
        if field.value in ("attributes", "capacity"):
            self.expect_op("[")
            domain = self.next()
            if domain.kind != "str":
                raise CelUnsupportedError(
                    "expected quoted domain in device."
                    f"{field.value}[...], got {domain.value!r}")
            self.expect_op("]")
            self.expect_op(".")
            name = self.next()
            if name.kind != "ident":
                raise CelUnsupportedError(
                    f"expected attribute name, got {name.value!r}")

            def node(env: _Env, _s=field.value, _d=domain.value,
                     _n=name.value, _raw=raw) -> Any:
                val = env.resolve(_s, _d, _n)
                if val is MISSING_DOMAIN and not _raw:
                    return _MISSING
                return val
            node.device_path = (field.value, domain.value, name.value)
            return node
        raise CelUnsupportedError(f"unsupported device field "
                                  f"{field.value!r}")

    def list_literal(self) -> List[Any]:
        self.expect_op("[")
        items: List[Any] = []
        if self._at_op("]"):
            self.next()
            return items
        while True:
            tok = self.next()
            if tok.kind == "op" and tok.value == "-":
                tok = self.next()
                if tok.kind != "int":
                    raise CelUnsupportedError(
                        f"expected int after - in list, got {tok.value!r}")
                if -tok.value < _INT64_MIN:
                    raise CelUnsupportedError(
                        f"int literal -{tok.value} exceeds int64")
                items.append(-tok.value)
            elif tok.kind in ("str", "int"):
                if tok.kind == "int" and tok.value > _INT64_MAX:
                    raise CelUnsupportedError(
                        f"int literal {tok.value} exceeds int64")
                items.append(tok.value)
            elif tok.kind == "ident" and tok.value in ("true", "false"):
                items.append(tok.value == "true")
            else:
                raise CelUnsupportedError(
                    f"unsupported list element {tok.value!r}")
            nxt = self.next()
            if nxt.kind == "op" and nxt.value == "]":
                return items
            if not (nxt.kind == "op" and nxt.value == ","):
                raise CelUnsupportedError(f"expected , or ] in list, got "
                                          f"{nxt.value!r}")

    # -- helpers -----------------------------------------------------------

    def _at_op(self, op: str) -> bool:
        tok = self.peek()
        return tok is not None and tok.kind == "op" and tok.value == op


def _check_re2_pattern(pattern: str):
    """The single matches() pattern validator, shared by the compiler
    (literal patterns: raised once at compile, cached as a compile
    error) and ``_cel_matches`` (dynamic patterns: raised per device).
    Returns the compiled regex on success."""
    if _NON_RE2_RE.search(pattern):
        raise CelUnsupportedError(
            f"matches() pattern {pattern!r} uses regex constructs RE2 "
            f"(the real CEL regex engine) rejects — backreferences, "
            f"lookaround, atomic/conditional groups, or possessive "
            f"quantifiers")
    try:
        return re.compile(pattern)
    except re.error as e:
        # Without an RE2 engine we cannot tell invalid-in-both (real
        # scheduler runtime-errors -> missing) from Python-only rejects
        # of valid RE2 (e.g. RE2's \z) — guessing either way can
        # silently diverge, so fail loud like any unsupported construct.
        raise CelUnsupportedError(
            f"matches() pattern {pattern!r} does not compile here "
            f"({e}); cannot faithfully mirror the RE2 verdict") from e


class IndexConstraint(NamedTuple):
    """One conjunctive equality constraint extracted from a compiled
    selector — the unit of an index probe plan.

    ``kind`` is ``"driver"`` (``device.driver == value``) or ``"attr"``
    (``device.attributes[domain].name == value``). Probes are PRUNING
    hints only: every device that matches the full selector necessarily
    satisfies each top-level conjunct, so intersecting index buckets can
    never exclude a true match — the full evaluation still runs on the
    survivors."""

    kind: str       # "driver" | "attr"
    domain: str     # attribute domain ("" for driver)
    name: str       # attribute name ("" for driver)
    value: Any      # str | bool


def _flatten_conjuncts(fn, out: List) -> None:
    conj = getattr(fn, "conjuncts", None)
    if conj is None:
        out.append(fn)
        return
    _flatten_conjuncts(conj[0], out)
    _flatten_conjuncts(conj[1], out)


def _extract_index_constraints(fn) -> "tuple[IndexConstraint, ...]":
    """Walk a compiled closure tree: top-level ``&&`` conjuncts that are
    ``<device path> == <str/bool literal>`` (either operand order) become
    probe constraints; everything else (||, !, ranges, method calls,
    capacity paths) is ignored — the probe plan is a subset of the
    selector's meaning, never a replacement for it."""
    terms: List = []
    _flatten_conjuncts(fn, terms)
    out: List[IndexConstraint] = []
    for term in terms:
        ops = getattr(term, "eq_operands", None)
        if ops is None:
            continue
        for side, other in (ops, ops[::-1]):
            path = getattr(side, "device_path", None)
            if path is None or not getattr(other, "const", False):
                continue
            value = other.value
            if not isinstance(value, (str, bool)):
                continue          # indexes cover string/bool equality keys
            section, domain, name = path
            if section == "driver" and isinstance(value, str):
                out.append(IndexConstraint("driver", "", "", value))
            elif section == "attributes":
                out.append(IndexConstraint("attr", domain, name, value))
            break
    return tuple(out)


class CompiledSelector:
    """A selector compiled to a closure tree: parse once, evaluate per
    device. Stateless across evaluations (every evaluate() gets a fresh
    ``_Env``), so one instance can serve every device of every request
    concurrently."""

    __slots__ = ("expression", "_fn", "_index_constraints")

    def __init__(self, expression: str, fn):
        self.expression = expression
        self._fn = fn
        self._index_constraints: Optional[tuple] = None

    def index_constraints(self) -> "tuple[IndexConstraint, ...]":
        """The selector's index probe plan: top-level conjunctive
        equality constraints over device.driver / device.attributes.
        Computed lazily and memoized on the instance — compiled
        selectors live in the bounded LRU, so the plan is cached
        alongside the compiled expression. Empty tuple = nothing
        extractable; callers must fall back to the full candidate
        set."""
        if self._index_constraints is None:
            self._index_constraints = _extract_index_constraints(self._fn)
        return self._index_constraints

    def evaluate(self, resolver: Resolver) -> bool:
        """Evaluate against one device. Raises CelUnsupportedError
        (value-dependent construct outside the subset) or CelEvalError
        (non-boolean result)."""
        result = self._fn(_Env(resolver))
        if result is _MISSING:
            return False
        if not isinstance(result, bool):
            raise CelEvalError(
                f"selector evaluated to non-boolean {result!r}")
        return result

    def __repr__(self) -> str:
        return f"CompiledSelector({self.expression!r})"


# ---------------------------------------------------------------------------
# Bounded compile cache. The allocator evaluates the SAME selector text
# against every candidate device of every request; keying on expression
# text (the resolver stays per-device, passed at evaluate time) makes
# the hot loop one parse per expression instead of one per device.
# Compile errors are cached AS errors: a selector that failed to compile
# re-raises the same error type/message on every hit without reparsing.
# ---------------------------------------------------------------------------

# Sized for fleet scale: a 1024-node fleet of node-pinned claim
# selectors is ~1024 distinct hot expressions, and a bound below the
# working set turns the LRU into a 100%-miss cycle (every allocation
# re-parses). Compiled closure trees are a few KB, so 4096 entries is
# single-digit MBs.
COMPILE_CACHE_MAXSIZE = 4096

_compile_cache: "OrderedDict[str, Any]" = OrderedDict()
_compile_cache_mu = threading.Lock()


def _compile_uncached(expression: str) -> CompiledSelector:
    return CompiledSelector(expression,
                            _Compiler(_tokenize(expression)).compile())


def compile_selector(expression: str, cached: bool = True) -> CompiledSelector:
    """Compile a selector, through the bounded LRU cache by default.
    Raises CelUnsupportedError/CelEvalError for expressions outside the
    subset — identically on cache hit and miss. ``cached=False``
    bypasses the cache entirely (benchmarking the reparse cost)."""
    if not cached:
        return _compile_uncached(expression)
    with _compile_cache_mu:
        entry = _compile_cache.get(expression)
        if entry is not None:
            _compile_cache.move_to_end(expression)
    if entry is not None:
        _metrics.CEL_COMPILE_CACHE_HITS.inc()
        if isinstance(entry, Exception):
            # a fresh instance (same type, same args => same message):
            # re-raising the cached object would accrete tracebacks
            raise type(entry)(*entry.args)
        return entry
    _metrics.CEL_COMPILE_CACHE_MISSES.inc()
    try:
        compiled: Any = _compile_uncached(expression)
    except (CelUnsupportedError, CelEvalError) as e:
        _cache_store(expression, e)
        raise
    _cache_store(expression, compiled)
    return compiled


def _cache_store(expression: str, entry: Any) -> None:
    with _compile_cache_mu:
        _compile_cache[expression] = entry
        _compile_cache.move_to_end(expression)
        while len(_compile_cache) > COMPILE_CACHE_MAXSIZE:
            _compile_cache.popitem(last=False)
            _metrics.CEL_COMPILE_CACHE_EVICTIONS.inc()


def clear_compile_cache() -> None:
    """Drop every cached compilation (tests and benchmarks)."""
    with _compile_cache_mu:
        _compile_cache.clear()


def compile_cache_info() -> dict:
    """Introspection for tests/benchmarks: current size, bound, and the
    process-lifetime hit/miss/eviction counter values."""
    with _compile_cache_mu:
        size = len(_compile_cache)
    return {
        "size": size,
        "maxsize": COMPILE_CACHE_MAXSIZE,
        "hits": _metrics.CEL_COMPILE_CACHE_HITS.value,
        "misses": _metrics.CEL_COMPILE_CACHE_MISSES.value,
        "evictions": _metrics.CEL_COMPILE_CACHE_EVICTIONS.value,
    }


def evaluate(expression: str, resolver: Resolver) -> bool:
    """Evaluate a selector expression to a boolean, compiling through
    the bounded LRU cache. Raises CelUnsupportedError (construct outside
    the subset) or CelEvalError (non-boolean result) — compile errors
    identically on cache hit and miss."""
    return compile_selector(expression).evaluate(resolver)
