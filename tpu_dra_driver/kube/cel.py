"""A small recursive-descent CEL evaluator for DRA device selectors.

The real scheduler evaluates full CEL against each device
(k8s.io/dynamic-resource-allocation/cel); the in-process allocator (the
scheduler stand-in for tests, demos, and the sim e2e suite) needs to
honor the same selectors that ship in `deviceclasses.yaml` and the
controller's claim templates — plus the shapes users realistically
write: `||`, `!`, parentheses, `in` over list literals.

Supported grammar (fail-loud `CelUnsupportedError` on anything else, so
a selector the allocator cannot faithfully evaluate never silently
matches or mismatches):

    expr   := or
    or     := and ( "||" and )*
    and    := unary ( "&&" unary )*
    unary  := "!" unary | cmp
    cmp    := operand ( ("=="|"!="|">="|"<="|">"|"<") operand
                       | "in" list )?
    operand:= literal | path | "(" expr ")"
    path   := "device" "." "driver"
            | "device" "." ("attributes"|"capacity") "[" string "]"
              "." ident
    list   := "[" ( literal ( "," literal )* )? "]"
    literal:= string | int | "true" | "false"

Semantics follow the scheduler where the driver depends on them:
attribute domains resolve within the publishing driver's domain; a
qualified domain that is not the device's driver yields a *missing*
value. Missing propagates the way a CEL runtime error does: through
comparisons (including ``!=``), ``in``, and ``!``; it is absorbed by
``&&`` when the other side is false and by ``||`` when the other side
is true (CEL's commutative short-circuit); a missing overall result
means the device does not match.
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, NamedTuple, Optional

# Sentinel for "attribute absent / wrong domain" — the public name is the
# resolver contract (allocator.py returns it); it behaves like a CEL
# runtime error during evaluation.
MISSING = object()
_MISSING = MISSING


class CelUnsupportedError(ValueError):
    """The expression uses CEL the in-process allocator does not speak."""


class CelEvalError(ValueError):
    """The expression parsed but evaluated to something non-boolean."""


class _Tok(NamedTuple):
    kind: str     # op | ident | str | int
    value: Any


_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<op>\|\||&&|==|!=|>=|<=|[!><()\[\],.])
    | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
    | (?P<int>-?\d+)
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    )""", re.X)


def _tokenize(src: str) -> List[_Tok]:
    toks: List[_Tok] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m or m.end() == pos:
            rest = src[pos:].strip()
            if not rest:
                break
            raise CelUnsupportedError(f"unsupported CEL at {rest[:40]!r}")
        pos = m.end()
        if m.group("op"):
            toks.append(_Tok("op", m.group("op")))
        elif m.group("str") is not None:
            raw = m.group("str")
            body = raw[1:-1]
            body = re.sub(r"\\(.)", r"\1", body)
            toks.append(_Tok("str", body))
        elif m.group("int") is not None:
            toks.append(_Tok("int", int(m.group("int"))))
        else:
            toks.append(_Tok("ident", m.group("ident")))
    return toks


# resolver(section, domain, name) -> value or _MISSING.
# section: "driver" (domain/name empty), "attributes", "capacity".
Resolver = Callable[[str, str, str], Any]


class _Parser:
    def __init__(self, toks: List[_Tok], resolver: Resolver):
        self.toks = toks
        self.i = 0
        self.resolve = resolver

    def peek(self) -> Optional[_Tok]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> _Tok:
        tok = self.peek()
        if tok is None:
            raise CelUnsupportedError("unexpected end of expression")
        self.i += 1
        return tok

    def expect_op(self, op: str) -> None:
        tok = self.next()
        if tok.kind != "op" or tok.value != op:
            raise CelUnsupportedError(f"expected {op!r}, got {tok.value!r}")

    # -- grammar -----------------------------------------------------------

    def parse(self) -> Any:
        val = self.or_expr()
        if self.peek() is not None:
            raise CelUnsupportedError(
                f"trailing tokens from {self.peek().value!r}")
        return val

    def or_expr(self) -> Any:
        val = self.and_expr()
        while self._at_op("||"):
            self.next()
            rhs = self.and_expr()   # evaluation is pure; combine after
            # CEL's commutative ||: true absorbs an error on either side
            a, b = self._boolish(val), self._boolish(rhs)
            if a is True or b is True:
                val = True
            elif a is _MISSING or b is _MISSING:
                val = _MISSING
            else:
                val = False
        return val

    def and_expr(self) -> Any:
        val = self.unary()
        while self._at_op("&&"):
            self.next()
            rhs = self.unary()
            # CEL's commutative &&: false absorbs an error on either side
            a, b = self._boolish(val), self._boolish(rhs)
            if a is False or b is False:
                val = False
            elif a is _MISSING or b is _MISSING:
                val = _MISSING
            else:
                val = True
        return val

    def unary(self) -> Any:
        if self._at_op("!"):
            self.next()
            val = self._boolish(self.unary())
            return _MISSING if val is _MISSING else not val
        return self.cmp()

    def cmp(self) -> Any:
        lhs = self.operand()
        tok = self.peek()
        if tok is None:
            return lhs
        if tok.kind == "op" and tok.value in ("==", "!=", ">", "<", ">=", "<="):
            op = self.next().value
            rhs = self.operand()
            return self._compare(op, lhs, rhs)
        if tok.kind == "ident" and tok.value == "in":
            self.next()
            items = self.list_literal()
            return _MISSING if lhs is _MISSING else lhs in items
        return lhs

    def operand(self) -> Any:
        tok = self.peek()
        if tok is None:
            raise CelUnsupportedError("unexpected end of expression")
        if tok.kind == "op" and tok.value == "(":
            self.next()
            val = self.or_expr()
            self.expect_op(")")
            return val
        if tok.kind in ("str", "int"):
            return self.next().value
        if tok.kind == "ident":
            if tok.value == "true":
                self.next()
                return True
            if tok.value == "false":
                self.next()
                return False
            if tok.value == "device":
                return self.device_path()
            raise CelUnsupportedError(f"unsupported identifier {tok.value!r}")
        raise CelUnsupportedError(f"unsupported token {tok.value!r}")

    def device_path(self) -> Any:
        self.next()              # device
        self.expect_op(".")
        field = self.next()
        if field.kind != "ident":
            raise CelUnsupportedError(f"expected field after device., got "
                                      f"{field.value!r}")
        if field.value == "driver":
            return self.resolve("driver", "", "")
        if field.value in ("attributes", "capacity"):
            self.expect_op("[")
            domain = self.next()
            if domain.kind != "str":
                raise CelUnsupportedError(
                    "expected quoted domain in device."
                    f"{field.value}[...], got {domain.value!r}")
            self.expect_op("]")
            self.expect_op(".")
            name = self.next()
            if name.kind != "ident":
                raise CelUnsupportedError(
                    f"expected attribute name, got {name.value!r}")
            return self.resolve(field.value, domain.value, name.value)
        raise CelUnsupportedError(f"unsupported device field "
                                  f"{field.value!r}")

    def list_literal(self) -> List[Any]:
        self.expect_op("[")
        items: List[Any] = []
        if self._at_op("]"):
            self.next()
            return items
        while True:
            tok = self.next()
            if tok.kind in ("str", "int"):
                items.append(tok.value)
            elif tok.kind == "ident" and tok.value in ("true", "false"):
                items.append(tok.value == "true")
            else:
                raise CelUnsupportedError(
                    f"unsupported list element {tok.value!r}")
            nxt = self.next()
            if nxt.kind == "op" and nxt.value == "]":
                return items
            if not (nxt.kind == "op" and nxt.value == ","):
                raise CelUnsupportedError(f"expected , or ] in list, got "
                                          f"{nxt.value!r}")

    # -- helpers -----------------------------------------------------------

    def _at_op(self, op: str) -> bool:
        tok = self.peek()
        return tok is not None and tok.kind == "op" and tok.value == op

    @staticmethod
    def _boolish(val: Any) -> Any:
        """True / False / _MISSING; anything else is a type error."""
        if val is _MISSING or isinstance(val, bool):
            return val
        raise CelEvalError(f"expected boolean, got {val!r}")

    @staticmethod
    def _compare(op: str, lhs: Any, rhs: Any) -> Any:
        if lhs is _MISSING or rhs is _MISSING:
            # a CEL runtime error (missing map key) propagates through
            # every comparison, != included
            return _MISSING
        if op == "==":
            return lhs == rhs
        if op == "!=":
            return lhs != rhs
        if not (isinstance(lhs, int) and not isinstance(lhs, bool)
                and isinstance(rhs, int) and not isinstance(rhs, bool)):
            raise CelUnsupportedError(
                f"ordered comparison needs ints, got {lhs!r} {op} {rhs!r}")
        return {"<": lhs < rhs, "<=": lhs <= rhs,
                ">": lhs > rhs, ">=": lhs >= rhs}[op]


def evaluate(expression: str, resolver: Resolver) -> bool:
    """Evaluate a selector expression to a boolean. Raises
    CelUnsupportedError (construct outside the subset) or CelEvalError
    (non-boolean result)."""
    result = _Parser(_tokenize(expression), resolver).parse()
    if result is _MISSING:
        return False
    if not isinstance(result, bool):
        raise CelEvalError(
            f"selector evaluated to non-boolean {result!r}")
    return result
