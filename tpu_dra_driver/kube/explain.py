"""Allocation decision explain records — why the allocator picked or parked.

Every claim through :meth:`Allocator.allocate_batch` can produce one
bounded structured record of the decision: the index-probe plan, the
candidate count each filter stage saw, a per-stage rejection histogram
(``selector-false``, ``counter-exhausted``, ``held-by-other``,
``fencing-stale``, ``remote-denied``), re-pick iterations, reservation
phase outcomes, and the final placement or the reason the claim will
park. Records live in a per-process bounded ring served at
``/debug/explain[/<claim-uid>]`` (pkg/metrics.py DebugHTTPServer), and
the top rejection reason is summarized into the ``AllocationParked``
Event body so a parked claim is actionable straight from ``kubectl
describe resourceclaim``.

Design rules (the tracing/faultinject discipline):

- **Disabled is free.** A module-global bool guards every entry point;
  the allocator's hot loop pays one ``is not None`` check per candidate
  and allocates nothing. The standalone/bench allocator paths never arm
  the ring; the allocation controller arms it at construction.
- **Eviction is never silent.** The ring is a fixed-capacity deque and
  every record pushed out ticks ``dra_explain_evicted_total`` — the
  FlightRecorder lesson (PR 8).
- **Reads are frozen.** Records enter the ring only when *finished*
  (immutable from then on) and ``payload()``/``lookup()`` copy the
  membership under the ring lock, so a reader racing a live batch sees
  a consistent prefix, never a half-built record.

The commit-phase helper (:func:`commit_phase`) also lives here: one
context manager that opens the ``allocator.commit.<phase>`` child span
AND observes ``dra_allocation_commit_phase_seconds{phase}`` with the
span's exemplar — allocator.py and reservations.py thread it through
the verify-read / status-write / reserve-phase1 / await-grants /
phase2-graduate / unwind legs of the commit path so the critical-path
analyzer and the doctor's ``COMMIT_STALL`` finding see the same split.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from tpu_dra_driver.pkg import metrics, tracing

#: records kept per process (one ring, shared by every allocator the
#: controller rebuilds across hand-offs — same reasoning as the shared
#: EventRecorder)
DEFAULT_CAPACITY = 256

#: the filter-stage taxonomy; every rejection a candidate or a claim
#: suffers is counted under exactly one of these
REJECTION_REASONS = ("selector-false", "counter-exhausted",
                     "held-by-other", "fencing-stale", "remote-denied")

#: the commit sub-segment taxonomy (span ``allocator.commit.<phase>``,
#: critical-path segment ``allocation.commit.<phase>``, histogram label
#: ``phase``) — keep the three surfaces in lockstep
COMMIT_PHASES = ("verify_read", "status_write", "reserve_phase1",
                 "await_grants", "phase2_graduate", "unwind")

EXPLAIN_EVICTED = metrics.DEFAULT_REGISTRY.counter(
    "dra_explain_evicted_total",
    "Allocation explain records pushed out of the bounded decision "
    "ring to make room for newer ones (served at /debug/explain; an "
    "evicted claim's decision trace is gone)")

_ENABLED = False
_RING: Optional["ExplainRing"] = None
_LOCAL = threading.local()


class RequestExplain:
    """The candidate funnel of ONE device request within a claim."""

    __slots__ = ("name", "count", "probe_constraints", "used_index",
                 "candidates", "rejections", "picked")

    def __init__(self, name: str, count: int):
        self.name = name
        self.count = count
        self.probe_constraints = 0
        self.used_index = False
        self.candidates = 0
        #: reason -> candidates rejected at that stage (plain dict; the
        #: pick loop increments it inline — one record is only ever
        #: mutated by the worker thread allocating its claim)
        self.rejections: Dict[str, int] = {}
        self.picked = 0

    def to_dict(self) -> Dict:
        return {
            "request": self.name,
            "count": self.count,
            "index_probe": {"constraints": self.probe_constraints,
                            "used_index": self.used_index},
            "candidates": self.candidates,
            "rejections": dict(self.rejections),
            "picked": self.picked,
        }


class ExplainRecord:
    """The decision trace of one claim through one allocation attempt."""

    __slots__ = ("claim_uid", "claim", "driver", "node", "started_unix",
                 "finished_unix", "requests", "repicks", "reservations",
                 "rejections", "outcome", "detail", "devices", "trace_id")

    def __init__(self, claim_uid: str, claim: str, driver: str,
                 node: Optional[str]):
        self.claim_uid = claim_uid
        self.claim = claim
        self.driver = driver
        self.node = node
        self.started_unix = time.time()
        self.finished_unix: Optional[float] = None
        self.requests: List[RequestExplain] = []
        self.repicks = 0
        #: reservation-phase outcomes, in order (local reserve verdicts,
        #: per-slot remote grant verdicts) — the two-phase protocol's
        #: visible footprint
        self.reservations: List[Dict] = []
        #: claim-level rejections with no per-candidate stage
        #: (fencing-stale, remote-denied at reserve time)
        self.rejections: Dict[str, int] = {}
        self.outcome = "in-flight"
        self.detail: Optional[str] = None
        self.devices: List[str] = []
        self.trace_id: Optional[str] = None

    # -- recording (worker thread only, no lock needed) -----------------

    def begin_request(self, name: str, count: int) -> RequestExplain:
        req = RequestExplain(name, count)
        self.requests.append(req)
        return req

    def note_rejection(self, reason: str, n: int = 1) -> None:
        self.rejections[reason] = self.rejections.get(reason, 0) + n

    def note_reservation(self, **outcome) -> None:
        self.reservations.append(outcome)

    # -- reading --------------------------------------------------------

    def rejection_totals(self) -> Dict[str, int]:
        """Claim-level + per-request rejections merged, reason -> count."""
        out = dict(self.rejections)
        for req in self.requests:
            for reason, n in req.rejections.items():
                out[reason] = out.get(reason, 0) + n
        return out

    def top_rejection(self) -> Optional[str]:
        totals = self.rejection_totals()
        if not totals:
            return None
        return max(totals, key=lambda r: (totals[r], r))

    def summary(self) -> str:
        """One actionable line for the AllocationParked Event body."""
        candidates = sum(r.candidates for r in self.requests)
        picked = sum(r.picked for r in self.requests)
        wanted = sum(r.count for r in self.requests)
        totals = self.rejection_totals()
        parts = [f"candidates={candidates}", f"picked={picked}/{wanted}"]
        if totals:
            rej = ",".join(f"{r}={totals[r]}"
                           for r in sorted(totals, key=totals.get,
                                           reverse=True))
            parts.append(f"rejected[{rej}]")
        if self.repicks:
            parts.append(f"repicks={self.repicks}")
        return " ".join(parts)

    def to_dict(self) -> Dict:
        dur = (None if self.finished_unix is None
               else round((self.finished_unix - self.started_unix) * 1e3, 3))
        return {
            "claim_uid": self.claim_uid,
            "claim": self.claim,
            "driver": self.driver,
            "node": self.node,
            "started_unix": self.started_unix,
            "finished_unix": self.finished_unix,
            "duration_ms": dur,
            "requests": [r.to_dict() for r in self.requests],
            "repicks": self.repicks,
            "reservations": list(self.reservations),
            "rejections": self.rejection_totals(),
            "top_rejection": self.top_rejection(),
            "outcome": self.outcome,
            "detail": self.detail,
            "devices": list(self.devices),
            "trace_id": self.trace_id,
            "summary": self.summary(),
        }


class ExplainRing:
    """Fixed-capacity ring of finished records, newest last, indexed by
    claim UID (latest attempt wins)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._records: deque = deque()
        self._by_uid: Dict[str, ExplainRecord] = {}
        self._mu = threading.Lock()

    def append(self, rec: ExplainRecord) -> None:
        with self._mu:
            self._records.append(rec)
            self._by_uid[rec.claim_uid] = rec
            while len(self._records) > self.capacity:
                evicted = self._records.popleft()
                if self._by_uid.get(evicted.claim_uid) is evicted:
                    del self._by_uid[evicted.claim_uid]
                EXPLAIN_EVICTED.inc()

    def __len__(self) -> int:
        with self._mu:
            return len(self._records)

    def lookup(self, claim_uid: str) -> Optional[Dict]:
        """The latest finished record for a claim UID, or None."""
        with self._mu:
            rec = self._by_uid.get(claim_uid)
        return rec.to_dict() if rec is not None else None

    def record(self, claim_uid: str) -> Optional[ExplainRecord]:
        with self._mu:
            return self._by_uid.get(claim_uid)

    def payload(self) -> Dict:
        """The /debug/explain body: a frozen copy of the membership —
        every listed record is finished and immutable."""
        with self._mu:
            records = list(self._records)
        return {
            "enabled": True,
            "capacity": self.capacity,
            "size": len(records),
            "evicted": EXPLAIN_EVICTED.value,
            "records": [r.to_dict() for r in reversed(records)],
        }

    def clear(self) -> None:
        with self._mu:
            self._records.clear()
            self._by_uid.clear()


# ---------------------------------------------------------------------------
# module API (the tracing configure/reset shape)
# ---------------------------------------------------------------------------

def configure(capacity: int = DEFAULT_CAPACITY) -> ExplainRing:
    """Arm the per-process decision ring (idempotent for the same
    capacity; a different capacity replaces the ring)."""
    global _ENABLED, _RING
    if _RING is None or _RING.capacity != int(capacity):
        _RING = ExplainRing(capacity)
    _ENABLED = True
    return _RING


def reset() -> None:
    """Disarm and drop the ring (tests)."""
    global _ENABLED, _RING
    _ENABLED = False
    _RING = None
    _LOCAL.rec = None


def enabled() -> bool:
    return _ENABLED


def ring() -> Optional[ExplainRing]:
    return _RING


def begin(claim: Dict, driver: str,
          node: Optional[str] = None) -> Optional[ExplainRecord]:
    """Open the decision record for one claim on this worker thread.
    Returns None (and allocates nothing) when explain is disarmed."""
    if not _ENABLED:
        return None
    meta = claim.get("metadata") or {}
    rec = ExplainRecord(
        meta.get("uid", ""),
        f"{meta.get('namespace', '')}/{meta.get('name', '')}",
        driver, node)
    _LOCAL.rec = rec
    return rec


def current() -> Optional[ExplainRecord]:
    """This worker thread's in-flight record (None when disarmed or no
    claim is being allocated) — reservations.py reports remote-denial
    through this without plumbing the record through the ledger API."""
    if not _ENABLED:
        return None
    return getattr(_LOCAL, "rec", None)


def finish(rec: Optional[ExplainRecord], outcome: str,
           detail: Optional[str] = None,
           devices: Optional[List[str]] = None,
           trace_id: Optional[str] = None) -> None:
    """Seal the record and publish it to the ring (it becomes immutable
    and reader-visible here, never earlier)."""
    if rec is None:
        return
    rec.finished_unix = time.time()
    rec.outcome = outcome
    rec.detail = detail
    if devices:
        rec.devices = list(devices)
    if trace_id:
        rec.trace_id = trace_id
    if getattr(_LOCAL, "rec", None) is rec:
        _LOCAL.rec = None
    ring_ = _RING
    if _ENABLED and ring_ is not None:
        ring_.append(rec)


def lookup(claim_uid: str) -> Optional[Dict]:
    """Latest finished record for a claim UID (controller Event
    enrichment + /debug/explain/<uid>)."""
    ring_ = _RING
    return ring_.lookup(claim_uid) if ring_ is not None else None


# ---------------------------------------------------------------------------
# commit-path micro-attribution
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def commit_phase(phase: str):
    """One commit sub-segment: opens the ``allocator.commit.<phase>``
    child span (critical-path segment ``allocation.commit.<phase>``) and
    observes ``dra_allocation_commit_phase_seconds{phase}`` with the
    span's exemplar. Metrics always record; the span is free when
    tracing is disabled."""
    t0 = time.perf_counter()
    with tracing.span("allocator.commit." + phase) as sp:
        try:
            yield sp
        finally:
            metrics.ALLOCATION_COMMIT_PHASE_SECONDS.labels(phase).observe(
                time.perf_counter() - t0, exemplar=tracing.exemplar(sp))
