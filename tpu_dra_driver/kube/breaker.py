"""API-server circuit breaker + per-verb retry budget for RestCluster.

Reference analog: client-go does not ship a circuit breaker — the
reference driver rides kubelet's own backoff when the API server browns
out. At the scale ROADMAP targets, that is not enough: a dead or
drowning API server must (a) stop being hammered by retries, and (b) be
*visible* to kubelet so it stops routing NodePrepareResources into a
backend that cannot resolve claims — the DRA health service reports
NOT_SERVING while the breaker is open (plugin/driver.py ``healthy()``).

Two cooperating pieces:

- :class:`CircuitBreaker` — CLOSED → OPEN after ``failure_threshold``
  consecutive request failures; OPEN fails fast (no network) for
  ``reset_timeout`` seconds; then HALF_OPEN admits exactly one probe
  request — success closes the breaker, failure re-opens it (and
  re-arms the timer). State is exported via the
  ``dra_circuit_breaker_state`` gauge (0/1/2) and transition counter.

- :class:`RetryBudget` — a token bucket per HTTP verb: each retry
  spends a token; tokens refill at ``refill_per_sec``. When the bucket
  runs dry, the request path stops retrying (returning the last
  response) and counts ``dra_retry_budget_exhausted_total{verb}`` —
  bounded amplification under brownout, where naive per-request retry
  ladders multiply load exactly when the server can least afford it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from tpu_dra_driver.kube.errors import ApiError
from tpu_dra_driver.pkg import metrics as _metrics

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpenError(ApiError):
    """Request rejected locally: the breaker is open (no network IO was
    attempted). Subclasses ApiError so existing retry/relist paths treat
    it like any other server-side failure."""

    code = 503


class CircuitBreaker:
    def __init__(self, name: str = "apiserver",
                 failure_threshold: int = 5,
                 reset_timeout: float = 30.0,
                 clock=time.monotonic):
        self.name = name
        self._threshold = failure_threshold
        self._reset_timeout = reset_timeout
        self._clock = clock
        self._mu = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        # Half-open probe admission is a time-bounded LEASE, not a latch:
        # a request path that dies between allow() and its record_* call
        # (an injected crash, an unexpected non-transport exception) must
        # not wedge the breaker into permanent fail-fast — after
        # reset_timeout the lease expires and the next probe is admitted.
        self._probe_in_flight = False
        self._probe_started = 0.0
        self._gauge = _metrics.CIRCUIT_BREAKER_STATE.labels(name)
        self._gauge.set(0)

    @property
    def state(self) -> str:
        with self._mu:
            # surface the timer expiry as half-open even before a probe
            # arrives, so health checks can report "probing" truthfully
            if (self._state == OPEN
                    and self._clock() - self._opened_at >= self._reset_timeout):
                self._transition(HALF_OPEN)
            return self._state

    def allow(self) -> bool:
        """Gate one request. False = fail fast without touching the
        network. In HALF_OPEN exactly one in-flight probe is admitted."""
        with self._mu:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self._reset_timeout:
                    return False
                self._transition(HALF_OPEN)
            # HALF_OPEN: one probe at a time, lease-bounded (see __init__)
            if (self._probe_in_flight
                    and self._clock() - self._probe_started
                    < self._reset_timeout):
                return False
            self._probe_in_flight = True
            self._probe_started = self._clock()
            return True

    def record_success(self) -> None:
        with self._mu:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._mu:
            self._probe_in_flight = False
            if self._state == HALF_OPEN:
                # failed probe: straight back to open, timer re-armed
                self._opened_at = self._clock()
                self._transition(OPEN)
                return
            self._consecutive_failures += 1
            if (self._state == CLOSED
                    and self._consecutive_failures >= self._threshold):
                self._opened_at = self._clock()
                self._transition(OPEN)

    def _transition(self, to: str) -> None:
        """Call with _mu held."""
        if self._state == to:
            return
        self._state = to
        self._gauge.set(_STATE_VALUE[to])
        _metrics.CIRCUIT_BREAKER_TRANSITIONS.labels(self.name, to).inc()


class RetryBudget:
    def __init__(self, capacity: float = 10.0, refill_per_sec: float = 1.0,
                 clock=time.monotonic):
        self._capacity = capacity
        self._refill = refill_per_sec
        self._clock = clock
        self._mu = threading.Lock()
        self._tokens: Dict[str, float] = {}
        self._stamp: Dict[str, float] = {}

    def try_spend(self, verb: str) -> bool:
        """One retry wants to happen for ``verb``. True = allowed (a
        token was spent); False = budget dry (counted in the exhausted
        metric — the caller must stop retrying)."""
        now = self._clock()
        with self._mu:
            tokens = self._tokens.get(verb, self._capacity)
            last = self._stamp.get(verb, now)
            tokens = min(self._capacity, tokens + (now - last) * self._refill)
            self._stamp[verb] = now
            if tokens >= 1.0:
                self._tokens[verb] = tokens - 1.0
                return True
            self._tokens[verb] = tokens
        _metrics.RETRY_BUDGET_EXHAUSTED.labels(verb).inc()
        return False

    def remaining(self, verb: str) -> float:
        now = self._clock()
        with self._mu:
            tokens = self._tokens.get(verb, self._capacity)
            last = self._stamp.get(verb, now)
            return min(self._capacity, tokens + (now - last) * self._refill)
