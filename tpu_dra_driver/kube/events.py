"""Kubernetes Event recorder: async, deduped, rate-limited, never-raising.

Reference analog: client-go's ``record.EventRecorder`` (an async
broadcaster) + the aggregation/spam-filter ``EventCorrelator``. The
reference driver never emits Events — a stuck claim shows nothing under
``kubectl describe resourceclaim``; this recorder closes that gap for
both driver names.

Semantics modeled on client-go where the driver depends on them:

- **Async emission**: :meth:`EventRecorder.event` only enqueues; a
  background worker performs the API writes. The prepare/allocate hot
  paths never block on the API server for an advisory Event (a slow
  apiserver must not push NodePrepareResources past kubelet's call
  timeout). Queue overflow drops (counted), bounded memory. Tests call
  :meth:`flush`.
- **Dedupe/aggregate**: a repeat of the same (object uid, reason,
  message, type) within ``dedupe_window`` bumps ``count`` +
  ``lastTimestamp`` (RFC3339 — a real API server rejects numeric
  metav1.Time) on the existing Event object instead of creating a new
  one (the correlator's aggregation).
- **Rate limit**: a token bucket PER INVOLVED OBJECT (burst 25, refill
  0.25/s — client-go's EventSourceObjectSpamFilter is keyed per
  source+object the same way), so one crash-looping claim cannot starve
  every other object's events. Over-budget emissions are *dropped*,
  counted in ``dra_events_emitted_total{outcome="dropped"}``.
  State-shaped reasons (:data:`ASSURED_REASONS`) bypass the bucket:
  their emitters dedupe to one Event per condition entry, and dropping
  one leaves a live condition with no Event an operator can see.
- **Never raise**: event emission is advisory; an API failure is
  counted (``outcome="error"``) and logged at debug, never propagated
  into the reconcile/prepare path that emitted it.

Backed by any :class:`~tpu_dra_driver.kube.client.ResourceClient` over
the ``events`` core resource — the in-memory FakeCluster and the REST
cluster both serve it, so the recorder works identically in unit tests,
the sim e2e harness, and a real cluster.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Dict

from tpu_dra_driver.kube.client import ResourceClient
from tpu_dra_driver.kube.errors import NotFoundError
from tpu_dra_driver.pkg import metrics as _metrics

log = logging.getLogger(__name__)

NORMAL = "Normal"
WARNING = "Warning"

# Event reasons emitted by the driver (the catalog documented in
# docs/observability.md; tests pin the load-bearing ones).
REASON_ALLOCATED = "Allocated"
REASON_ALLOCATION_FAILED = "AllocationFailed"
REASON_ALLOCATION_PARKED = "AllocationParked"
REASON_PREPARED = "Prepared"
REASON_PREPARE_FAILED = "PrepareFailed"
REASON_UNPREPARED = "Unprepared"
REASON_UNPREPARE_FAILED = "UnprepareFailed"
REASON_CD_READY = "CDReady"
REASON_VALIDATION_FAILED = "ValidationFailed"
REASON_SLO_BURN_RATE = "SLOBurnRate"

#: STATE-SHAPED reasons exempt from the per-object token bucket. Their
#: emitters already dedupe to one Event per condition ENTRY (the
#: allocation controller emits AllocationParked once per parked
#: lifecycle and clears it when the claim drains), so their volume is
#: bounded by condition transitions — not by a crash loop — and a
#: DROPPED one breaks an operator-visibility invariant: the condition
#: exists with no Event saying so. The 10k-node COW soak (ISSUE 12,
#: seed 20260804) caught exactly that: once snapshots stopped costing
#: O(fleet), route flapping during a 30 s lease-flap window cycled
#: park/clear fast enough to drain the claim's bucket, and the FINAL
#: park's Warning was rate-limited away — a live parked claim with no
#: AllocationParked Event. The bucket is per involved object, so this
#: exemption cannot let one object starve another's events.
ASSURED_REASONS = frozenset({REASON_ALLOCATION_PARKED})

#: Worker threads exit after this long idle and respawn on demand, so
#: short-lived recorders (benches, tests) don't accumulate parked threads.
_WORKER_IDLE_EXIT = 30.0

#: Queue sentinel marking a clear() request (delete emitted Events for an
#: object+reason) rather than an emission.
_CLEAR = object()

#: Queue sentinel marking an assure() request (verify state-shaped
#: Events still exist; recreate only the lost ones) rather than an
#: emission.
_ASSURE = object()


def _rfc3339(ts: float) -> str:
    """metav1.Time wire form — a real API server rejects numeric
    timestamps (400, cannot unmarshal number into v1.Time), and the
    recorder's never-raise contract would swallow that into silence.
    Seconds precision, UTC, lexicographically ordered."""
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def object_ref(kind: str, name: str, namespace: str = "",
               uid: str = "") -> Dict[str, str]:
    ref = {"kind": kind, "name": name}
    if namespace:
        ref["namespace"] = namespace
    if uid:
        ref["uid"] = uid
    return ref


def ref_from_obj(obj: Dict, kind: str = "") -> Dict[str, str]:
    """involvedObject ref from a full k8s object dict."""
    meta = obj.get("metadata") or {}
    return object_ref(kind or obj.get("kind", ""), meta.get("name", ""),
                      meta.get("namespace", ""), meta.get("uid", ""))


def normalize_claim_refs(claim_refs) -> Dict[str, Dict[str, str]]:
    """uid → ``{"uid", "name", "namespace"}`` from the two shapes the
    plugin unprepare APIs accept: bare uid strings (unit tests, older
    callers) or full ref dicts (the gRPC layer, which has kubelet's
    name/namespace and passes them so Events can name the claim)."""
    out: Dict[str, Dict[str, str]] = {}
    for r in claim_refs:
        if isinstance(r, dict):
            out[r["uid"]] = {"uid": r["uid"], "name": r.get("name", ""),
                             "namespace": r.get("namespace", "")}
        else:
            out[r] = {"uid": r, "name": "", "namespace": ""}
    return out


def emit_claim_event(recorder: "EventRecorder", node_name: str,
                     ref: Dict[str, str], action: str,
                     error=None, permanent: bool = False) -> None:
    """The one claim-lifecycle Event shape both kubelet plugins emit.
    ``action``: "prepared" | "released" (the CD plugin's spelling) |
    "unprepared". Nameless refs (bare-uid callers) have nothing for
    kubectl describe to find — skipped."""
    name = ref.get("name", "")
    if not name:
        return
    obj = object_ref("ResourceClaim", name, ref.get("namespace", ""),
                     ref.get("uid", ""))
    if action == "unprepared":
        if error is None:
            recorder.normal(obj, REASON_UNPREPARED,
                            f"unprepared on node {node_name}")
        else:
            recorder.warning(obj, REASON_UNPREPARE_FAILED,
                             f"unprepare failed on node {node_name}: "
                             f"{error}")
        return
    if error is None:
        recorder.normal(obj, REASON_PREPARED,
                        f"{action} on node {node_name}")
    else:
        recorder.warning(obj, REASON_PREPARE_FAILED,
                         f"prepare {'permanently ' if permanent else ''}"
                         f"failed on node {node_name}: {error}")


class EventRecorder:
    def __init__(self, events: ResourceClient,
                 component: str = "tpu-dra-driver",
                 host: str = "",
                 dedupe_window: float = 600.0,
                 burst: int = 25,
                 refill_per_sec: float = 0.25,
                 cache_max: int = 512,
                 queue_max: int = 2048):
        self._events = events
        self._component = component
        self._host = host
        self._window = dedupe_window
        self._mu = threading.Lock()
        # dedupe key -> {"name": event object name, "namespace": ns,
        #                "count": n, "last": monotonic ts}
        self._cache: "OrderedDict[tuple, Dict]" = OrderedDict()
        self._cache_max = cache_max
        # PER-OBJECT token buckets (client-go spam-filter keying): one
        # noisy object exhausts only its own budget. LRU-bounded.
        self._burst = float(burst)
        self._refill = refill_per_sec
        self._buckets: "OrderedDict[str, list]" = OrderedDict()
        # async emission: event() enqueues, one lazy worker drains
        self._qcond = threading.Condition()
        self._queue: deque = deque()
        self._queue_max = queue_max
        self._inflight = 0
        self._worker = None
        self._closed = False

    # ------------------------------------------------------------------
    # enqueue side (the hot path: no API IO, no lock beyond the queue)
    # ------------------------------------------------------------------

    def event(self, involved: Dict, type_: str, reason: str,
              message: str) -> None:
        """Queue one Event against ``involved`` (a full object dict or an
        involvedObject-shaped ref) for async emission. Never raises, never
        blocks on the API server."""
        try:
            ref = (ref_from_obj(involved) if "metadata" in involved
                   else dict(involved))
        except Exception:  # chaos-ok: events are advisory, counted
            _metrics.EVENTS_EMITTED.labels(reason, "error").inc()
            return
        with self._qcond:
            if self._closed or len(self._queue) >= self._queue_max:
                _metrics.EVENTS_EMITTED.labels(reason, "dropped").inc()
                return
            self._queue.append((ref, type_, reason, message))
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._drain, daemon=True,
                    name=f"event-recorder-{self._component}")
                self._worker.start()
            self._qcond.notify_all()

    def normal(self, involved: Dict, reason: str, message: str) -> None:
        self.event(involved, NORMAL, reason, message)

    def warning(self, involved: Dict, reason: str, message: str) -> None:
        self.event(involved, WARNING, reason, message)

    def clear(self, involved: Dict, reason: str) -> None:
        """Queue deletion of every Event previously emitted against
        ``involved`` with ``reason`` — for *state-shaped* events
        (AllocationParked) whose condition has drained: the Event must
        stop being what ``kubectl describe`` shows. Async, never raises,
        never blocks; a later re-emission recreates the Event."""
        try:
            ref = (ref_from_obj(involved) if "metadata" in involved
                   else dict(involved))
        except Exception:  # chaos-ok: events are advisory, counted
            _metrics.EVENTS_EMITTED.labels(reason, "error").inc()
            return
        with self._qcond:
            if self._closed or len(self._queue) >= self._queue_max:
                _metrics.EVENTS_EMITTED.labels(reason, "dropped").inc()
                return
            self._queue.append((_CLEAR, ref, reason))
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._drain, daemon=True,
                    name=f"event-recorder-{self._component}")
                self._worker.start()
            self._qcond.notify_all()

    def assure(self, namespace: str, reason: str, entries) -> None:
        """Queue an existence check for state-shaped Events: for each
        ``(involvedObject ref, message)`` in ``entries`` (one shared
        ``namespace``), verify an Event with ``reason`` from THIS
        reportingInstance still exists, and recreate it only if it was
        lost (queue overflow under an event storm once dropped a park
        Warning whose emitter fires only on first entry into the
        condition — the 10k COW soak's finding). Worker-side this costs
        one Event LIST per call plus an API write per *genuinely
        missing* Event, so callers may re-assert every live condition
        on a periodic tick without O(conditions) write amplification —
        and without minting duplicates when a dedupe-cache entry was
        LRU-evicted while the Event object survived. Async, never
        raises, never blocks."""
        with self._qcond:
            if self._closed or len(self._queue) >= self._queue_max:
                _metrics.EVENTS_EMITTED.labels(reason, "dropped").inc()
                return
            self._queue.append((_ASSURE, namespace, reason,
                                tuple((dict(r), m) for r, m in entries)))
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._drain, daemon=True,
                    name=f"event-recorder-{self._component}")
                self._worker.start()
            self._qcond.notify_all()

    def queue_depth(self) -> int:
        """Queued-plus-inflight emissions right now — the leak-sentinel
        surface: a recorder whose queue depth grows monotonically across
        a long run is backed up behind a slow/sick API server (or a dead
        worker), and will start dropping events at ``queue_max``."""
        with self._qcond:
            return len(self._queue) + self._inflight

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every queued event is emitted (tests and orderly
        shutdown); True when the queue fully drained in time."""
        deadline = time.monotonic() + timeout
        with self._qcond:
            while self._queue or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._qcond.wait(timeout=min(left, 0.05))
            return True

    def stop(self, timeout: float = 2.0) -> None:
        """Flush (bounded) then CLOSE the recorder: the worker thread
        exits promptly and later enqueues are dropped (counted).

        Without this, a shut-down component's worker lingered for up to
        ``_WORKER_IDLE_EXIT`` (30 s) — harmless when the process exits
        with the component, but an in-process restart (drills, the
        fleet scenarios' servicing, shard hand-offs rebuilding
        cross-shard allocators) strands one worker per cycle. Caught by
        the endurance soak's thread sentinel (compressed-week seed 11:
        monotone 42 → 49 threads across epochs 3-6, every extra one an
        ``event-recorder-*``); every component shutdown path now calls
        this."""
        self.flush(timeout=timeout)
        with self._qcond:
            self._closed = True
            self._qcond.notify_all()
        worker = self._worker
        if worker is not None:
            worker.join(timeout=timeout)

    # ------------------------------------------------------------------
    # worker side
    # ------------------------------------------------------------------

    def _drain(self) -> None:
        while True:
            with self._qcond:
                if not self._queue:
                    if not self._closed:
                        self._qcond.wait(timeout=_WORKER_IDLE_EXIT)
                    if not self._queue:
                        self._worker = None   # idle/closed: exit
                        return
                item = self._queue.popleft()
                self._inflight += 1
            try:
                if item[0] is _CLEAR:
                    self._clear_emitted(item[1], item[2])
                elif item[0] is _ASSURE:
                    self._assure_emitted(item[1], item[2], item[3])
                else:
                    self._emit(*item)
            except Exception:  # chaos-ok: events are advisory, counted
                _metrics.EVENTS_EMITTED.labels(item[2], "error").inc()
                log.debug("event %s emission failed", item[2], exc_info=True)
            finally:
                with self._qcond:
                    self._inflight -= 1
                    self._qcond.notify_all()

    def _take_token(self, obj_key: str) -> bool:
        """One token from ``obj_key``'s bucket (created full on first
        use; LRU-bounded alongside the dedupe cache)."""
        now = time.monotonic()
        with self._mu:
            bucket = self._buckets.get(obj_key)
            if bucket is None:
                bucket = [self._burst, now]
                self._buckets[obj_key] = bucket
            tokens, last = bucket
            tokens = min(self._burst, tokens + (now - last) * self._refill)
            if tokens < 1.0:
                bucket[0], bucket[1] = tokens, now
                return False
            bucket[0], bucket[1] = tokens - 1.0, now
            self._buckets.move_to_end(obj_key)
            while len(self._buckets) > self._cache_max:
                self._buckets.popitem(last=False)
            return True

    def _emit(self, ref: Dict, type_: str, reason: str,
              message: str) -> None:
        namespace = ref.get("namespace") or "default"
        obj_key = ref.get("uid") or f"{namespace}/{ref.get('name', '')}"
        key = (obj_key, ref.get("kind", ""), type_, reason, message)
        now = time.monotonic()
        with self._mu:
            cached = self._cache.get(key)
            dedupe_target = (dict(cached) if cached is not None
                             and now - cached["last"] <= self._window
                             else None)
        if reason not in ASSURED_REASONS and not self._take_token(obj_key):
            _metrics.EVENTS_EMITTED.labels(reason, "dropped").inc()
            return

        if dedupe_target is not None:
            if self._bump(dedupe_target, key, now):
                return
            # the aggregated Event object is gone (GC'd): recreate below

        wall = _rfc3339(time.time())
        obj = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"generateName": f"{ref.get('name') or 'object'}.",
                         "namespace": namespace},
            "type": type_,
            "reason": reason,
            "message": message,
            "count": 1,
            "firstTimestamp": wall,
            "lastTimestamp": wall,
            "involvedObject": ref,
            "source": {"component": self._component,
                       **({"host": self._host} if self._host else {})},
            "reportingComponent": self._component,
            "reportingInstance": self._host or self._component,
        }
        created = self._events.create(obj)
        with self._mu:
            self._cache[key] = {
                "name": created["metadata"]["name"],
                "namespace": namespace, "count": 1, "last": now,
            }
            self._cache.move_to_end(key)
            while len(self._cache) > self._cache_max:
                self._cache.popitem(last=False)
        _metrics.EVENTS_EMITTED.labels(reason, "created").inc()

    def _clear_emitted(self, ref: Dict, reason: str) -> None:
        """Worker side of :meth:`clear`: delete matching Event objects and
        forget their dedupe entries so a re-park emits fresh.

        Scoped to THIS recorder's ``reportingInstance``: in a
        multi-replica control plane two controllers can independently
        track one claim's parked state (e.g. a re-route mid-park, or a
        demoted stale holder clearing its queues while the survivor
        still parks the claim) — deleting a RIVAL's Event would blind
        operators to a condition that very much still exists."""
        namespace = ref.get("namespace") or "default"
        obj_key = ref.get("uid") or f"{namespace}/{ref.get('name', '')}"
        instance = self._host or self._component
        removed = 0
        for ev in self._events.list(namespace=namespace):
            if ev.get("reason") != reason:
                continue
            if ev.get("reportingInstance", instance) != instance:
                continue
            inv = ev.get("involvedObject") or {}
            match = (inv.get("uid") == ref["uid"] if ref.get("uid")
                     and inv.get("uid")
                     else inv.get("name") == ref.get("name")
                     and inv.get("namespace", "") == ref.get("namespace", ""))
            if not match:
                continue
            self._events.delete_ignore_missing(
                ev["metadata"]["name"], namespace)
            removed += 1
        with self._mu:
            for key in [k for k in self._cache
                        if k[0] == obj_key and k[3] == reason]:
                del self._cache[key]
        if removed:
            _metrics.EVENTS_EMITTED.labels(reason, "cleared").inc(removed)

    def _assure_emitted(self, namespace: str, reason: str,
                        entries) -> None:
        """Worker side of :meth:`assure`: one LIST, then per entry —
        found: re-seed the dedupe cache (so the next emission
        aggregates onto the surviving object) and write nothing;
        missing: recreate through the normal emit path. Instance-scoped
        like :meth:`_clear_emitted` — a rival replica's Event does not
        count as ours existing."""
        ns = namespace or "default"
        instance = self._host or self._component
        # index the candidates once: a capacity crunch can park
        # thousands of claims, and a per-entry linear scan would stall
        # the (single) recorder worker for the whole tick
        by_uid: Dict[str, Dict] = {}
        by_name: Dict[tuple, Dict] = {}         # any event, for uid-less refs
        by_name_nouid: Dict[tuple, Dict] = {}   # uid-less events only — a
        # uid-bearing ref must NOT adopt a same-name event for a
        # different uid (stale event of a deleted+recreated claim)
        for ev_obj in self._events.list(namespace=ns):
            if ev_obj.get("reason") != reason:
                continue
            if ev_obj.get("reportingInstance", instance) != instance:
                continue
            inv = ev_obj.get("involvedObject") or {}
            nkey = (inv.get("name", ""), inv.get("namespace", ""))
            by_name.setdefault(nkey, ev_obj)
            if inv.get("uid"):
                by_uid.setdefault(inv["uid"], ev_obj)
            else:
                by_name_nouid.setdefault(nkey, ev_obj)
        for ref, message in entries:
            nkey = (ref.get("name", ""), ref.get("namespace", ""))
            if ref.get("uid"):
                found = by_uid.get(ref["uid"]) or by_name_nouid.get(nkey)
            else:
                found = by_name.get(nkey)
            obj_key = ref.get("uid") or f"{ns}/{ref.get('name', '')}"
            if found is not None:
                key = (obj_key, ref.get("kind", ""), WARNING, reason,
                       message)
                with self._mu:
                    if key not in self._cache:
                        self._cache[key] = {
                            "name": found["metadata"]["name"],
                            "namespace": ns,
                            "count": int(found.get("count") or 1),
                            "last": time.monotonic(),
                        }
                        self._cache.move_to_end(key)
                        while len(self._cache) > self._cache_max:
                            self._cache.popitem(last=False)
                continue
            # the Event is gone while its condition lives: drop stale
            # dedupe entries (they name the deleted object) and recreate
            with self._mu:
                for k in [k for k in self._cache
                          if k[0] == obj_key and k[3] == reason]:
                    del self._cache[k]
            log.info("re-asserting lost %s Event for %s/%s", reason,
                     ref.get("namespace", ""), ref.get("name", ""))
            self._emit(ref, WARNING, reason, message)

    def _bump(self, cached: Dict, key: tuple, now: float) -> bool:
        """Aggregate a repeat onto the existing Event object; False when
        that object no longer exists."""
        def mutate(obj):
            obj["count"] = int(obj.get("count") or 1) + 1
            obj["lastTimestamp"] = _rfc3339(time.time())
        try:
            self._events.retry_update(cached["name"], cached["namespace"],
                                      mutate)
        except NotFoundError:
            with self._mu:
                self._cache.pop(key, None)
            return False
        with self._mu:
            entry = self._cache.get(key)
            if entry is not None:
                entry["count"] += 1
                entry["last"] = now
                self._cache.move_to_end(key)
        _metrics.EVENTS_EMITTED.labels(key[3], "deduped").inc()
        return True
