"""Indexed device catalog + incremental usage ledger for the allocator.

Reference analog: the structured-parameters allocator in kube-scheduler
(k8s.io/dynamic-resource-allocation/structured) walks every device in
every ResourceSlice per pending claim and re-derives cluster usage from a
full claim LIST — O(nodes x devices x claims). client-go's answer at
scale is indexed listers over shared-informer stores plus a scheduler
snapshot; this module is that shape for the in-repo allocator:

- :class:`DeviceCatalog`: a shared-informer-fed cache of every published
  device keyed ``(pool, device)``, maintaining secondary indexes over
  driver name, node, pool, and a configurable set of string/bool
  attribute equality keys. Watch events update the indexes incrementally
  (one slice's devices are re-indexed, nothing else is touched); a watch
  RELIST rebuilds them from the informer store in one pass (the
  ``catalog.index-rebuild`` fault point fires there).
- :class:`CatalogSnapshot`: an immutable per-allocation-batch view,
  obtained as a near-O(1) copy-on-write *pin* of the catalog's current
  generation (structural sharing via :mod:`tpu_dra_driver.kube.cow`;
  slice events pay for the delta, snapshots pay nothing) — candidate
  sets come from index intersection
  (:meth:`CatalogSnapshot.candidates`) instead of a fleet scan, with the
  full set as fallback when a selector has no extractable constraint.
  Probes are PRUNING hints: the full selector still evaluates on every
  survivor, so index and linear paths pick identical winners.
- :class:`UsageLedger`: allocated-device + counter usage fed by the
  claim informer (allocate/deallocate deltas keyed by claim UID — a
  claim observed twice counts once, and a claim whose allocation was
  removed stops counting even while stale ``reservedFor`` entries linger
  in its status), with in-flight reservations so parallel allocation
  workers under one process can never double-commit a device or
  oversubscribe a shared counter.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from tpu_dra_driver.kube import cel
from tpu_dra_driver.kube import cow
from tpu_dra_driver.kube.client import ResourceClient
from tpu_dra_driver.kube.informer import Informer
from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg.metrics import (
    CATALOG_BUCKET_CLONES,
    CATALOG_GENERATIONS,
    CATALOG_SNAPSHOT_SECONDS,
    SWALLOWED_ERRORS,
)

# Pre-bound metric children: the COW bookkeeping sits on the slice-event
# path and the snapshot pin sits on every batch — no .labels() dict
# lookup per call.
_CLONES = {f: CATALOG_BUCKET_CLONES.labels(f)
           for f in ("toplevel", "pool", "driver", "node", "attr",
                     "ledger")}
_GEN_CATALOG = CATALOG_GENERATIONS.labels("catalog")
_GEN_LEDGER = CATALOG_GENERATIONS.labels("ledger")
_SNAP_SECONDS = {s: CATALOG_SNAPSHOT_SECONDS.labels(s)
                 for s in ("catalog", "catalog-copy", "ledger",
                           "ledger-copy")}

fi.register("catalog.index-rebuild",
            "one full index rebuild after a watch RELIST (fail models a "
            "rebuild dying mid-way: indexes must stay at their pre-gap "
            "state and the next relist must converge)")

DeviceKey = Tuple[str, str]          # (pool name, device name)
# Counter usage/capacity is scoped by pool: the reference publisher names
# counter sets per chip INDEX ("tpu-0-counter-set"), so an unscoped key
# would conflate chip 0 of every node in the fleet.
CounterKey = Tuple[str, str, str]    # (pool, counterSet name, counter name)

#: Attribute names indexed by default — the equality keys real claim
#: selectors discriminate on (chip type/generation, sub-slice shape, and
#: node identity: the publisher stamps every device with its node's name,
#: so scheduler-pinned claims resolve to one pool via an index probe).
DEFAULT_INDEX_ATTRIBUTES = ("type", "chipType", "subsliceShape",
                            "generation", "node")


def attr_value(dev: Dict, name: str):
    """A device attribute's wire value (string/int/bool/version box)."""
    a = (dev.get("attributes") or {}).get(name)
    if a is None:
        return None
    for k in ("string", "int", "bool", "version"):
        if k in a:
            return a[k]
    return None


def qty_int(value) -> int:
    """Counter/capacity value -> exact int; raises ValueError on
    non-integral quantities (counters are whole units)."""
    if isinstance(value, int):
        return value
    q = cel.Quantity(str(value))
    if not q.isInteger():
        raise ValueError(f"counter value {value!r} is not integral")
    return q.asInteger()


def device_counter_consumption(dev: Dict, pool: str) -> Dict[CounterKey, int]:
    """(pool, counterSet, counter) -> amount this device consumes."""
    out: Dict[CounterKey, int] = {}
    for cc in dev.get("consumesCounters") or []:
        cs = cc["counterSet"]
        for cname, cval in (cc.get("counters") or {}).items():
            ck = (pool, cs, cname)
            out[ck] = out.get(ck, 0) + qty_int(cval["value"])
    return out


def sum_counter_consumption(pairs: "Iterable[Tuple[Optional[Dict], str]]"
                            ) -> Dict[CounterKey, int]:
    """Aggregate (device dict or None, pool) pairs into one pool-scoped
    usage dict — the single accumulation used by committed claims,
    recomputes, and reservations, so counter scoping can never
    desynchronize between them."""
    out: Dict[CounterKey, int] = {}
    for dev, pool in pairs:
        if dev is None:
            continue
        for ck, amount in device_counter_consumption(dev, pool).items():
            out[ck] = out.get(ck, 0) + amount
    return out


class DeviceEntry:
    """One published device plus the slice context allocation needs."""

    __slots__ = ("key", "device", "driver", "node", "pool", "slice_name",
                 "order")

    def __init__(self, key: DeviceKey, device: Dict, driver: str, node: str,
                 pool: str, slice_name: str, order: Tuple[str, int]):
        self.key = key
        self.device = device
        self.driver = driver
        self.node = node
        self.pool = pool
        self.slice_name = slice_name
        # canonical scan order (slice name, position in slice): index and
        # linear candidate walks sort by this, so both pick the same
        # winners for the same fleet
        self.order = order


class _IndexState:
    """The mutable device-level index set, copy-on-write. NOT
    thread-safe — the catalog serializes access under its own lock; the
    static snapshot path uses a private instance.

    Structure: devices live in per-pool sub-maps (``pools``), secondary
    indexes are :class:`~tpu_dra_driver.kube.cow.Bucket` instances
    (per-pool sub-maps themselves). :meth:`snapshot` *pins* the current
    generation in O(1) — nothing is copied; the first mutation after a
    pin shallow-copies the top-level dicts and then clones only the
    buckets/sub-maps it actually touches (``_owned`` tracks what this
    generation already owns), so slice events pay O(their delta) and a
    pinned snapshot stays frozen forever."""

    def __init__(self, index_attributes: Iterable[str]):
        self.index_attributes = frozenset(index_attributes)
        #: pool name -> {device name -> DeviceEntry} (the device store)
        self.pools: Dict[str, Dict[str, DeviceEntry]] = {}
        self.n_devices = 0
        self.by_driver: Dict[str, cow.Bucket] = {}
        self.by_node: Dict[str, cow.Bucket] = {}
        self.by_attr: Dict[Tuple[str, object], cow.Bucket] = {}
        self.counter_caps: Dict[CounterKey, int] = {}
        # per-slice contributions, for clean incremental removal —
        # mutation bookkeeping only, never referenced by snapshots
        self._slice_keys: Dict[str, List[DeviceKey]] = {}
        self._slice_caps: Dict[str, Dict[CounterKey, int]] = {}
        self.version = 0
        #: True while a snapshot pins the current structures
        self._shared = False
        #: buckets/sub-maps cloned (hence privately owned) since the
        #: last pin — tokens ("pool", p) / (family, bkey[, pool])
        self._owned: Set[Tuple] = set()

    # -- copy-on-write bookkeeping ----------------------------------------

    def _prepare_write(self) -> None:
        """First mutation after a snapshot pin: shallow-copy the
        top-level dicts (pointer copies) so the pinned generation keeps
        the originals; inner buckets/sub-maps stay shared until
        individually touched."""
        if not self._shared:
            return
        self._shared = False
        self._owned.clear()
        self.pools = dict(self.pools)
        self.by_driver = dict(self.by_driver)
        self.by_node = dict(self.by_node)
        self.by_attr = dict(self.by_attr)
        self.counter_caps = dict(self.counter_caps)
        _CLONES["toplevel"].inc()

    def _pool_map(self, pool: str) -> Dict[str, DeviceEntry]:
        """The writable device sub-map for ``pool`` (cloned lazily on
        first touch per generation)."""
        sub = self.pools.get(pool)
        token = ("pool", pool)
        if sub is None:
            sub = self.pools[pool] = {}
            self._owned.add(token)
        elif token not in self._owned:
            sub = self.pools[pool] = dict(sub)
            self._owned.add(token)
            _CLONES["pool"].inc()
        return sub

    def _bucket(self, family: str, index: Dict, bkey) -> cow.Bucket:
        """The writable bucket ``index[bkey]`` (cloned lazily)."""
        b = index.get(bkey)
        token = (family, bkey)
        if b is None:
            b = index[bkey] = cow.Bucket()
            self._owned.add(token)
        elif token not in self._owned:
            b = index[bkey] = b.clone()
            self._owned.add(token)
            _CLONES[family].inc()
        return b

    def _bucket_pool(self, family: str, bkey, b: cow.Bucket,
                     pool: str) -> Dict[str, DeviceEntry]:
        """The writable per-pool sub-map of an owned bucket."""
        sub = b.pools.get(pool)
        token = (family, bkey, pool)
        if sub is None:
            sub = b.pools[pool] = {}
            self._owned.add(token)
        elif token not in self._owned:
            sub = b.pools[pool] = dict(sub)
            self._owned.add(token)
            _CLONES[family].inc()
        return sub

    # -- mutation ----------------------------------------------------------

    def add_slice(self, obj: Dict) -> None:
        self._prepare_write()
        name = obj["metadata"]["name"]
        self._remove_slice_impl(name)
        spec = obj.get("spec") or {}
        driver = spec.get("driver", "")
        node = spec.get("nodeName", "")
        pool = (spec.get("pool") or {}).get("name", "")
        keys: List[DeviceKey] = []
        devices = spec.get("devices") or []
        sub = self._pool_map(pool) if devices else None
        for i, dev in enumerate(devices):
            key = (pool, dev["name"])
            entry = DeviceEntry(key, dev, driver, node, pool, name,
                                (name, i))
            # a later slice claiming an existing key replaces it (the
            # API server enforces pool/device uniqueness; last-writer
            # wins here keeps the cache converging regardless)
            old = sub.get(dev["name"])
            if old is not None:
                self._deindex(old)
            else:
                self.n_devices += 1
            sub[dev["name"]] = entry
            self._index(entry)
            keys.append(key)
        caps: Dict[CounterKey, int] = {}
        for cs in spec.get("sharedCounters") or []:
            for cname, cval in (cs.get("counters") or {}).items():
                ck = (pool, cs["name"], cname)
                caps[ck] = caps.get(ck, 0) + qty_int(cval["value"])
        for ck, amount in caps.items():
            self.counter_caps[ck] = self.counter_caps.get(ck, 0) + amount
        self._slice_keys[name] = keys
        self._slice_caps[name] = caps
        self.version += 1

    def remove_slice(self, name: str) -> None:
        if name not in self._slice_keys:
            return
        self._prepare_write()
        self._remove_slice_impl(name)
        self.version += 1

    def _remove_slice_impl(self, name: str) -> None:
        keys = self._slice_keys.pop(name, None)
        if keys is None:
            return
        by_pool: Dict[str, List[DeviceKey]] = {}
        for key in keys:
            by_pool.setdefault(key[0], []).append(key)
        for pool, pkeys in by_pool.items():
            if pool not in self.pools:
                continue
            sub = self._pool_map(pool)
            for key in pkeys:
                entry = sub.get(key[1])
                if entry is not None and entry.slice_name == name:
                    self._deindex(entry)
                    del sub[key[1]]
                    self.n_devices -= 1
            if not sub:
                del self.pools[pool]
        for ck, amount in self._slice_caps.pop(name, {}).items():
            left = self.counter_caps.get(ck, 0) - amount
            if left > 0:
                self.counter_caps[ck] = left
            else:
                self.counter_caps.pop(ck, None)

    def rebuild(self, slices: Iterable[Dict]) -> None:
        """Full rebuild (watch RELIST): re-derive everything from a
        fresh slice list into private structures, then adopt them
        wholesale — ONE atomic generation step. ``version`` bumps
        exactly once per rebuild (it used to bump once per slice PLUS
        once at the end, churning version-keyed caches — the allocation
        controller's route snapshots — N+1 times per resync)."""
        fresh = _IndexState(self.index_attributes)
        for obj in sorted(slices, key=lambda o: o["metadata"]["name"]):
            fresh.add_slice(obj)
        self.pools = fresh.pools
        self.n_devices = fresh.n_devices
        self.by_driver = fresh.by_driver
        self.by_node = fresh.by_node
        self.by_attr = fresh.by_attr
        self.counter_caps = fresh.counter_caps
        self._slice_keys = fresh._slice_keys
        self._slice_caps = fresh._slice_caps
        # the adopted structures are private to this state; anything a
        # snapshot pinned before stays frozen in that snapshot. Adopt
        # fresh's ownership tokens too (same format — fresh built
        # everything through the same helpers): clearing them instead
        # would make the first post-RELIST touch of every bucket/
        # sub-map pay a clone of an already-private structure.
        self._shared = False
        self._owned = fresh._owned
        self.version += 1

    def _index(self, entry: DeviceEntry) -> None:
        self._bucket_insert("driver", self.by_driver, entry.driver, entry)
        if entry.node:
            self._bucket_insert("node", self.by_node, entry.node, entry)
        for name in self.index_attributes:
            v = attr_value(entry.device, name)
            if isinstance(v, (str, bool)):
                self._bucket_insert("attr", self.by_attr, (name, v), entry)

    def _bucket_insert(self, family: str, index: Dict, bkey,
                       entry: DeviceEntry) -> None:
        b = self._bucket(family, index, bkey)
        sub = self._bucket_pool(family, bkey, b, entry.pool)
        name = entry.key[1]
        if name not in sub:
            b.count += 1
        sub[name] = entry
        b._sorted = None

    def _deindex(self, entry: DeviceEntry) -> None:
        self._bucket_remove("driver", self.by_driver, entry.driver, entry)
        if entry.node:
            self._bucket_remove("node", self.by_node, entry.node, entry)
        for name in self.index_attributes:
            v = attr_value(entry.device, name)
            if isinstance(v, (str, bool)):
                self._bucket_remove("attr", self.by_attr, (name, v), entry)

    def _bucket_remove(self, family: str, index: Dict, bkey,
                       entry: DeviceEntry) -> None:
        existing = index.get(bkey)
        if existing is None or not existing.contains(entry.key):
            return
        b = self._bucket(family, index, bkey)
        sub = self._bucket_pool(family, bkey, b, entry.pool)
        if entry.key[1] in sub:
            del sub[entry.key[1]]
            b.count -= 1
            b._sorted = None
        if not sub:
            del b.pools[entry.pool]
        if b.count == 0:
            del index[bkey]

    # -- read --------------------------------------------------------------

    def snapshot(self) -> "CatalogSnapshot":
        """Pin the current generation — O(1), nothing copied."""
        self._shared = True
        return CatalogSnapshot(
            pools=self.pools,
            n_devices=self.n_devices,
            by_driver=self.by_driver,
            by_node=self.by_node,
            by_attr=self.by_attr,
            counter_caps=self.counter_caps,
            index_attributes=self.index_attributes,
            version=self.version,
        )

    def copy_snapshot(self) -> "CatalogSnapshot":
        """The copying-baseline arm: every family deep-copied eagerly —
        the historical per-batch cost profile, kept for the bench's
        comparison arm and the winner-parity property (COW and copying
        snapshots must pick byte-identical winners)."""
        return CatalogSnapshot(
            pools={p: dict(sub) for p, sub in self.pools.items()},
            n_devices=self.n_devices,
            by_driver={k: b.deep_clone()
                       for k, b in self.by_driver.items()},
            by_node={k: b.deep_clone() for k, b in self.by_node.items()},
            by_attr={k: b.deep_clone() for k, b in self.by_attr.items()},
            counter_caps=dict(self.counter_caps),
            index_attributes=self.index_attributes,
            version=self.version,
        )


class CatalogSnapshot:
    """An immutable, structurally-shared view of the catalog for one
    allocation batch.

    Construction is a near-O(1) *pin* of the catalog's current
    generation — nothing is copied. The catalog clones whatever a later
    mutation touches (kube/cow.py), so concurrent updates never mutate
    a pinned snapshot and a batch allocates against one consistent
    fleet state. Candidate lists are memoized per (driver, node, probe
    plan): a batch of claims sharing one selector materializes and
    orders its candidate set exactly once. Callers must treat returned
    entry lists as read-only."""

    __slots__ = ("_pools", "devices", "by_driver", "by_node", "by_attr",
                 "counter_caps", "index_attributes", "version", "_memo")

    #: bound on the per-snapshot candidates memo (a snapshot lives for
    #: one batch; distinct probe plans per batch are few)
    MEMO_MAX = 4096

    def __init__(self, pools, n_devices, by_driver, by_node, by_attr,
                 counter_caps, index_attributes, version):
        self._pools: Dict[str, Dict[str, DeviceEntry]] = pools
        #: flat (pool, device) -> entry mapping view (shared storage)
        self.devices = cow.DeviceMap(pools, n_devices)
        self.by_driver: Dict[str, cow.Bucket] = by_driver
        self.by_node: Dict[str, cow.Bucket] = by_node
        self.by_attr: Dict[Tuple[str, object], cow.Bucket] = by_attr
        self.counter_caps: Dict[CounterKey, int] = counter_caps
        self.index_attributes = index_attributes
        self.version = version
        # per-snapshot candidates memo; benign GIL-atomic races only
        self._memo: Dict[Tuple, Tuple[List[DeviceEntry], bool]] = {}

    def has_driver(self, driver: str) -> bool:
        b = self.by_driver.get(driver)
        return b is not None and b.count > 0

    def pool_names(self):
        """Names of every pool with at least one published device —
        O(pools), no device iteration (the shard-gauge path)."""
        return self._pools.keys()

    def candidates(self, driver: str, node_name: Optional[str],
                   constraints: Tuple[cel.IndexConstraint, ...]
                   ) -> Tuple[List[DeviceEntry], bool]:
        """Candidate devices for one request, in canonical scan order.

        Returns ``(entries, used_index)``: ``used_index`` is True when at
        least one constraint pruned through an index (or proved the set
        empty). The result is a SUPERSET of the true matches — the
        caller still evaluates the full selector per candidate — and is
        memoized per probe plan for the snapshot's lifetime."""
        memo_key = (driver, node_name, constraints)
        got = self._memo.get(memo_key)
        if got is None:
            got = self._candidates(driver, node_name, constraints)
            if len(self._memo) < self.MEMO_MAX:
                self._memo[memo_key] = got
        return got

    def _candidates(self, driver: str, node_name: Optional[str],
                    constraints: Tuple[cel.IndexConstraint, ...]
                    ) -> Tuple[List[DeviceEntry], bool]:
        base = self.by_driver.get(driver)
        if base is None or not base.count:
            return [], False
        buckets: List[cow.Bucket] = [base]
        if node_name is not None:
            buckets.append(self.by_node.get(node_name) or cow.EMPTY_BUCKET)
        used_index = False
        for c in constraints:
            if c.kind == "driver":
                if c.value != driver:
                    # device.driver == <other driver> can never match a
                    # device this driver published
                    return [], True
                used_index = True
            elif c.kind == "attr":
                if c.domain and c.domain != driver:
                    # a qualified domain that is not the publishing
                    # driver's resolves to missing on every device ->
                    # the equality conjunct can never hold
                    return [], True
                if c.name in self.index_attributes:
                    buckets.append(self.by_attr.get((c.name, c.value))
                                   or cow.EMPTY_BUCKET)
                    used_index = True
        # iterate the smallest bucket's pre-sorted entries (sorted once
        # per bucket generation) and filter by membership in the rest —
        # no per-request sort of the merged result
        smallest = min(buckets, key=len)
        if not smallest.count:
            return [], used_index
        others = [b for b in buckets if b is not smallest]
        if others:
            entries = [e for e in smallest.sorted_entries()
                       if all(b.contains(e.key) for b in others)]
        else:
            entries = list(smallest.sorted_entries())
        return entries, used_index

    def all_candidates(self, driver: str, node_name: Optional[str]
                       ) -> List[DeviceEntry]:
        """The linear-fallback candidate set (driver + node filter only)."""
        entries, _ = self.candidates(driver, node_name, ())
        return entries

    def get_device(self, key: DeviceKey) -> Optional[Dict]:
        sub = self._pools.get(key[0])
        if sub is None:
            return None
        entry = sub.get(key[1])
        return entry.device if entry is not None else None


def build_snapshot(slices: Iterable[Dict],
                   index_attributes: Iterable[str] = DEFAULT_INDEX_ATTRIBUTES
                   ) -> CatalogSnapshot:
    """One-shot snapshot from a plain slice list — the catalog-less
    path (tests, demos, the linear bench arm) shares the exact index and
    ordering semantics of the live informer-fed catalog."""
    state = _IndexState(index_attributes)
    for obj in slices:
        state.add_slice(obj)
    return state.snapshot()


class _CatalogInformer(Informer):
    """Informer whose RELIST reconciliation additionally triggers a full
    catalog index rebuild (client-go's indexers are rebuilt the same way
    on relist). The diff-dispatch to handlers still runs — the catalog
    ignores those per-object events for a pass it already rebuilt."""

    def __init__(self, *args, on_relist: Callable[[List[Dict]], None],
                 **kwargs):
        super().__init__(*args, **kwargs)
        self._on_relist = on_relist

    def _resync(self, items: List[Dict]) -> None:
        super()._resync(items)
        # the rebuild must see the same filtered view the store keeps
        self._on_relist([o for o in items if self._accept(o)])


class DeviceCatalog:
    """Shared-informer-fed device cache with attribute indexes.

    ``start()`` lists+watches ResourceSlices; every watch event
    re-indexes exactly the touched slice's devices. ``snapshot()`` hands
    the allocator an immutable per-batch view."""

    def __init__(self, client: ResourceClient,
                 index_attributes: Iterable[str] = DEFAULT_INDEX_ATTRIBUTES,
                 slice_filter: Optional[Callable[[Dict], bool]] = None):
        self._client = client
        self._mu = threading.Lock()
        self._state = _IndexState(index_attributes)
        # A shard replica can scope its catalog to the slices whose pools
        # it owns (slice_filter on the informer): snapshots, indexes, and
        # RELIST rebuilds then cost O(owned fleet), not O(whole fleet).
        self.informer = _CatalogInformer(client, on_relist=self._on_relist,
                                         object_filter=slice_filter)
        self.informer.add_handlers(on_add=self._on_upsert,
                                   on_update=lambda old, new:
                                   self._on_upsert(new),
                                   on_delete=self._on_delete)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.informer.start()

    def stop(self) -> None:
        self.informer.stop()

    def wait_synced(self, timeout: float = 5.0) -> bool:
        return self.informer.wait_synced(timeout)

    # -- event handlers ----------------------------------------------------

    def _on_upsert(self, obj: Dict) -> None:
        with self._mu:
            self._state.add_slice(obj)

    def _on_delete(self, obj: Dict) -> None:
        with self._mu:
            self._state.remove_slice(obj["metadata"]["name"])

    def _on_relist(self, items: List[Dict]) -> None:
        """Full rebuild after a watch gap. A rebuild that dies mid-way
        (the ``catalog.index-rebuild`` fault point) must leave the
        PREVIOUS indexes intact — a fresh state swaps in atomically only
        on success; the informer's next relist converges."""
        try:
            items = fi.fire("catalog.index-rebuild", payload=items)
            fresh = _IndexState(self._state.index_attributes)
            fresh.rebuild(items or [])
        except Exception:  # chaos-ok: counted; next RELIST heals
            SWALLOWED_ERRORS.labels("catalog.index-rebuild").inc()
            import logging
            logging.getLogger(__name__).exception(
                "catalog index rebuild failed; keeping previous indexes "
                "until the next relist")
            return
        with self._mu:
            fresh.version = self._state.version + 1
            self._state = fresh

    # -- read --------------------------------------------------------------

    def snapshot(self) -> CatalogSnapshot:
        t0 = time.perf_counter()
        with self._mu:
            fresh_generation = not self._state._shared
            snap = self._state.snapshot()
        if fresh_generation:
            _GEN_CATALOG.inc()
        _SNAP_SECONDS["catalog"].observe(time.perf_counter() - t0)
        return snap

    def copy_snapshot(self) -> CatalogSnapshot:
        """The copying-baseline arm (bench comparison + parity tests):
        a full eager copy of every index family."""
        t0 = time.perf_counter()
        with self._mu:
            snap = self._state.copy_snapshot()
        _SNAP_SECONDS["catalog-copy"].observe(time.perf_counter() - t0)
        return snap

    def get_device(self, key: DeviceKey) -> Optional[Dict]:
        with self._mu:
            sub = self._state.pools.get(key[0])
            entry = sub.get(key[1]) if sub is not None else None
            return entry.device if entry is not None else None

    @property
    def version(self) -> int:
        with self._mu:
            return self._state.version


# ---------------------------------------------------------------------------
# Incremental usage ledger
# ---------------------------------------------------------------------------


class _ClaimRecord:
    __slots__ = ("keys", "counters", "all_keys", "rv")

    def __init__(self, keys: Tuple[DeviceKey, ...],
                 counters: Dict[CounterKey, int],
                 all_keys: Optional[Tuple[DeviceKey, ...]] = None,
                 rv: int = -1):
        #: keys this ledger ACCOUNTS for (pool-filtered under sharding)
        self.keys = keys
        self.counters = counters
        #: every key the claim holds, unfiltered — conflict checks
        #: (held_by_other) look here so a foreign-pool device held by
        #: another claim is still a conflict
        self.all_keys = keys if all_keys is None else all_keys
        #: resourceVersion of the observation that produced this record
        #: (-1 = unknown): an OLDER observation of the same claim must
        #: never overwrite a newer one — the ledger hears about a claim
        #: from two racing sources (the allocator's commit-side observe
        #: and the informer dispatch queue), and the informer's stale
        #: pre-allocation event arriving after the commit used to erase
        #: the committed record and double-allocate the device
        self.rv = rv


def _claim_rv(claim: Dict) -> int:
    try:
        return int((claim.get("metadata") or {}).get("resourceVersion"))
    except (TypeError, ValueError):
        return -1


def claim_allocated_keys(claim: Dict, driver: str) -> Tuple[DeviceKey, ...]:
    """Device keys a claim holds: from ``status.allocation`` ONLY —
    never from ``reservedFor`` (consumer references are not device
    ownership; a deallocated claim with stale reservedFor entries holds
    nothing) — deduplicated, adminAccess results excluded."""
    alloc = ((claim.get("status") or {}).get("allocation") or {})
    seen: Dict[DeviceKey, None] = {}
    for r in (alloc.get("devices") or {}).get("results") or []:
        if r.get("driver") == driver and not r.get("adminAccess"):
            seen.setdefault((r.get("pool", ""), r.get("device", "")))
    return tuple(seen)


class UsageLedger:
    """Cluster usage maintained from claim deltas instead of per-call
    LISTs. Keyed by claim UID: re-observing a claim (informer MODIFIED,
    a RELIST replay, or the allocator's own commit) replaces its prior
    contribution instead of double-counting."""

    def __init__(self, driver_name: str,
                 device_lookup: Callable[[DeviceKey], Optional[Dict]],
                 pool_filter: Optional[Callable[[str], bool]] = None):
        self._driver = driver_name
        self._lookup = device_lookup
        #: True while a snapshot pins _taken/_usage (copy-on-write:
        #: the next mutation clones both dicts, the pinned views stay
        #: frozen — see snapshot())
        self._snap_shared = False
        # Sharding hook: when set, only devices in pools the filter
        # accepts count toward this ledger's taken/usage aggregates —
        # each shard's ledger is then the single serialization point for
        # its own pools, and a cross-shard merged view can sum ledgers
        # without double counting (kube/sharding.py).
        self._pool_filter = pool_filter
        self._mu = threading.Lock()
        self._claims: Dict[str, _ClaimRecord] = {}
        self._taken: Dict[DeviceKey, int] = {}
        self._usage: Dict[CounterKey, int] = {}
        # in-flight reservations by an allocation worker that has picked
        # devices but not yet committed: uid -> record
        self._reserved: Dict[str, _ClaimRecord] = {}
        self._reserved_keys: Dict[DeviceKey, str] = {}
        # >0 while reservations are paused (set_pool_filter's re-derive,
        # or a controller's whole slot-adoption sequence): committed
        # devices in newly-acquired pools are not all in _taken yet, so
        # reserve() must fail safe (claims re-park and retry) instead of
        # treating them as free
        self._pause_reservations = 0
        # uids of DELETED claims (bounded FIFO): claim uids are never
        # reused, so any observation arriving after the delete is stale
        # by definition. Without this, a descheduled worker's commit-side
        # observe_claim could land AFTER the informer processed the
        # claim's DELETED event and resurrect a record for a claim that
        # no longer exists — a permanently leaked device holding.
        self._tombstones: "OrderedDict[str, None]" = OrderedDict()

    # -- informer feed -----------------------------------------------------

    def attach(self, informer: Informer) -> None:
        informer.add_handlers(on_add=self.observe_claim,
                              on_update=lambda old, new:
                              self.observe_claim(new),
                              on_delete=self.forget_claim)

    def _filter_keys(self, keys: Tuple[DeviceKey, ...]
                     ) -> Tuple[DeviceKey, ...]:
        if self._pool_filter is None:
            return keys
        return tuple(k for k in keys if self._pool_filter(k[0]))

    def observe_claim(self, claim: Dict) -> None:
        uid = (claim.get("metadata") or {}).get("uid", "")
        if not uid:
            return
        with self._mu:
            if uid in self._tombstones:
                return      # deleted claim: any later observation is stale
        rv = _claim_rv(claim)
        all_keys = claim_allocated_keys(claim, self._driver)
        if not all_keys:
            # Unallocated observation: drop any committed contribution
            # (deallocation) but KEEP an in-flight reservation — the
            # reservation is allocation-side state owned by the worker
            # between reserve() and commit, and a stale pre-allocation
            # event replayed by an informer (another shard's claim
            # informer, a RELIST resync) must not wipe it. Wiping it
            # here let a concurrent claim reserve the same device and
            # DOUBLE-ALLOCATE (caught by the fleet-scenario invariant).
            # Only forget_claim (a real DELETE) releases reservations.
            with self._mu:
                if self._stale_locked(uid, rv):
                    return
                self._remove_locked(uid)
                if rv >= 0:
                    # keep an empty-keyed marker carrying the
                    # deallocation's rv: without it, a LATE commit-side
                    # observe with an older rv (worker descheduled
                    # across the deallocation) finds no record to
                    # compare against and resurrects the stale holdings
                    self._claims[uid] = _ClaimRecord((), {}, all_keys=(),
                                                     rv=rv)
            return
        keys = self._filter_keys(all_keys)
        counters = sum_counter_consumption(
            (self._lookup(key), key[0]) for key in keys)
        with self._mu:
            # re-check the tombstone: the claim may have been DELETED
            # between the entry check and here (the counter lookups run
            # unlocked) — recording now would resurrect a dead claim's
            # holdings forever
            if uid in self._tombstones or self._stale_locked(uid, rv):
                return
            self._remove_locked(uid)
            self._release_locked(uid)
            rec = _ClaimRecord(keys, counters, all_keys=all_keys, rv=rv)
            self._claims[uid] = rec
            self._apply_locked(rec, +1)

    def _stale_locked(self, uid: str, rv: int) -> bool:
        """True when a recorded observation of ``uid`` is NEWER than
        ``rv`` — the incoming event is a stale replay and must not win.
        Unknown versions (-1) are never treated as stale."""
        existing = self._claims.get(uid)
        return (existing is not None and rv >= 0
                and existing.rv >= 0 and rv < existing.rv)

    def forget_claim(self, claim: Dict) -> None:
        uid = (claim.get("metadata") or {}).get("uid", "")
        if uid:
            self._forget(uid)

    def recompute_counters(self) -> None:
        """Re-derive counter usage for every held claim through the
        device lookup — called after a catalog rebuild or slice churn so
        usage tracks device definitions that arrived late."""
        with self._mu:
            uids = {uid: rec.keys for uid, rec in self._claims.items()}
        for uid, keys in uids.items():
            counters = sum_counter_consumption(
                (self._lookup(key), key[0]) for key in keys)
            with self._mu:
                rec = self._claims.get(uid)
                if rec is not None and rec.keys == keys:
                    self._apply_locked(rec, -1)
                    rec.counters = counters
                    self._apply_locked(rec, +1)

    def set_pool_filter(self,
                        pool_filter: Optional[Callable[[str], bool]]
                        ) -> None:
        """Swap the pool filter and re-derive every claim's accounted
        contribution (the shard hand-off path: a controller that just
        acquired a slot starts accounting for its pools). Reservations
        are REFUSED for the duration: until the re-derive lands, a
        device committed in a newly-acquired pool is absent from _taken
        and would look free — the churn scenario double-allocated
        through exactly that window. (The derive itself cannot run under
        _mu: counter lookups take the catalog informer's lock, which
        dispatch threads hold while calling into this ledger.)"""
        with self.reservations_paused():
            with self._mu:
                self._pool_filter = pool_filter
                uids = {uid: rec.all_keys
                        for uid, rec in self._claims.items()}
            for uid, all_keys in uids.items():
                keys = self._filter_keys(all_keys)
                counters = sum_counter_consumption(
                    (self._lookup(key), key[0]) for key in keys)
                with self._mu:
                    rec = self._claims.get(uid)
                    if rec is not None and rec.all_keys == all_keys:
                        self._apply_locked(rec, -1)
                        rec.keys = keys
                        rec.counters = counters
                        self._apply_locked(rec, +1)

    @contextmanager
    def reservations_paused(self):
        """Refuse new reservations for the duration (reentrant): the
        slot-adoption path wraps its WHOLE sequence — flipping the owned
        set, dropping cached cross-shard allocators, re-deriving the
        accounted keys — so no reserve can slip through a half-adopted
        view and double-allocate a device."""
        with self._mu:
            self._pause_reservations += 1
        try:
            yield
        finally:
            with self._mu:
                self._pause_reservations -= 1

    # -- allocation-side reservations -------------------------------------

    def reserve(self, uid: str, entries: List[DeviceEntry],
                caps: Dict[CounterKey, int],
                extend: bool = False) -> bool:
        """Atomically reserve devices an allocation worker picked, IF
        they are all still free and their counters still fit under
        ``caps`` given current usage + other reservations. False means
        the worker raced another claim and must re-pick.

        ``extend=True`` widens an existing same-uid reservation instead
        of refusing it — the reservation granter's case: a cross-replica
        claim spanning two slots of ONE owner arrives as two records,
        and the second must join the first (the new keys are still
        checked free/fitting; any other caller keeps the refusal)."""
        if self._pool_filter is not None and any(
                not self._pool_filter(e.pool) for e in entries):
            # not this ledger's pool: reservations must serialize through
            # the OWNING slot's ledger (stale routing re-parks and
            # re-routes on the next fleet change)
            return False
        keys = tuple(e.key for e in entries)
        counters = sum_counter_consumption(
            (e.device, e.pool) for e in entries)
        with self._mu:
            if self._pause_reservations:
                # mid-hand-off re-derive: _taken is incomplete for the
                # acquired pools — fail safe, the claim re-parks
                return False
            if extend and uid in self._reserved:
                return self._extend_reservation_locked(uid, entries, caps)
            if uid in self._reserved:
                # a CONCURRENT allocation attempt for this claim already
                # holds a reservation (two controllers can briefly both
                # route a claim home while their catalogs skew during
                # fleet churn). Releasing-and-replacing here would free
                # the first attempt's devices WHILE ITS COMMIT IS IN
                # FLIGHT — a third claim could then reserve one of them
                # and double-allocate (the churn scenario caught this).
                # Refuse instead: this attempt fails cleanly, the claim
                # parks, and the winner's committed allocation re-routes
                # it out of every queue. Every reserve is paired with a
                # release/graduation on all code paths, so a refused
                # attempt can never wedge the claim permanently.
                return False
            for key in keys:
                if self._taken.get(key) or key in self._reserved_keys:
                    return False
            for ck, amount in counters.items():
                cap = caps.get(ck)
                if cap is None or self._usage.get(ck, 0) + amount > cap:
                    return False
            rec = _ClaimRecord(keys, counters)
            self._reserved[uid] = rec
            for key in keys:
                self._reserved_keys[key] = uid
            self._apply_locked(rec, +1)
            return True

    def _extend_reservation_locked(self, uid: str,
                                   entries: List[DeviceEntry],
                                   caps: Dict[CounterKey, int]) -> bool:
        """Widen uid's existing reservation by ``entries`` (idempotent
        for keys it already holds — counters counted for genuinely new
        keys only). Call with _mu held."""
        rec = self._reserved[uid]
        new_entries = [e for e in entries
                       if self._reserved_keys.get(e.key) != uid]
        if not new_entries:
            return True
        new_keys = tuple(e.key for e in new_entries)
        for key in new_keys:
            if self._taken.get(key) or key in self._reserved_keys:
                return False
        new_counters = sum_counter_consumption(
            (e.device, e.pool) for e in new_entries)
        for ck, amount in new_counters.items():
            cap = caps.get(ck)
            if cap is None or self._usage.get(ck, 0) + amount > cap:
                return False
        self._apply_locked(rec, -1)
        rec.keys = rec.keys + new_keys
        rec.all_keys = rec.keys
        for ck, amount in new_counters.items():
            rec.counters[ck] = rec.counters.get(ck, 0) + amount
        for key in new_keys:
            self._reserved_keys[key] = uid
        self._apply_locked(rec, +1)
        return True

    def release(self, uid: str) -> None:
        """Drop an in-flight reservation (commit failed or abandoned)."""
        with self._mu:
            self._release_locked(uid)

    def shrink_reservation(self, uid: str,
                           entries: List[DeviceEntry]) -> None:
        """Remove ONLY ``entries``' keys from uid's reservation (the
        reverse of an ``extend``): the granter's per-record rollback —
        a failed grant for one record of a two-slot claim must not free
        the keys a previously-GRANTED record still holds. Dropping the
        last key releases the whole reservation."""
        with self._mu:
            rec = self._reserved.get(uid)
            if rec is None:
                return
            held = set(rec.keys)
            removed = [e for e in entries if e.key in held]
            if not removed:
                return
            drop = {e.key for e in removed}
            keep = tuple(k for k in rec.keys if k not in drop)
            if not keep:
                self._release_locked(uid)
                return
            removed_counters = sum_counter_consumption(
                (e.device, e.pool) for e in removed)
            self._apply_locked(rec, -1)
            rec.keys = keep
            rec.all_keys = keep
            for ck, amount in removed_counters.items():
                left = rec.counters.get(ck, 0) - amount
                if left > 0:
                    rec.counters[ck] = left
                else:
                    rec.counters.pop(ck, None)
            for key in drop:
                if self._reserved_keys.get(key) == uid:
                    del self._reserved_keys[key]
            self._apply_locked(rec, +1)

    # -- reads -------------------------------------------------------------

    def snapshot(self) -> Tuple[Set[DeviceKey], Dict[CounterKey, int]]:
        """(taken device keys, counter usage) including reservations.

        Copy-on-write: this is an O(1) *pin* — the returned views
        reference the live dicts, and the next ledger mutation clones
        them first (``_apply_locked``), so what a caller holds is
        frozen at pin time. Both views are READ-ONLY for the caller;
        the allocator's batch state overlays its own in-batch
        consumption instead of mutating them. The taken view is a dict
        keys-view (set-comparable, O(1) membership)."""
        t0 = time.perf_counter()
        with self._mu:
            if not self._snap_shared:
                self._snap_shared = True
                _GEN_LEDGER.inc()
            taken, usage = self._taken.keys(), self._usage
        _SNAP_SECONDS["ledger"].observe(time.perf_counter() - t0)
        return taken, usage

    def copy_snapshot(self) -> Tuple[Set[DeviceKey], Dict[CounterKey, int]]:
        """The historical copying snapshot (bench comparison arm +
        winner-parity tests): independent mutable copies."""
        t0 = time.perf_counter()
        with self._mu:
            taken, usage = set(self._taken), dict(self._usage)
        _SNAP_SECONDS["ledger-copy"].observe(time.perf_counter() - t0)
        return taken, usage

    def holdings(self, uid: str) -> Tuple[DeviceKey, ...]:
        with self._mu:
            rec = self._claims.get(uid)
            return rec.keys if rec is not None else ()

    def committed_keys(self) -> Set[DeviceKey]:
        """Device keys held by COMMITTED claims only (no in-flight
        reservations) — the consistency-invariant surface: committed
        holdings must exactly mirror the API server's allocated claims,
        while reservations are transient by design."""
        with self._mu:
            return {k for rec in self._claims.values() for k in rec.keys}

    def held_by_other(self, keys: Iterable[DeviceKey], uid: str) -> bool:
        """True if any of ``keys`` is held (committed claim or in-flight
        reservation) by a claim other than ``uid`` — the verify-on-commit
        question."""
        wanted = set(keys)
        with self._mu:
            for other_uid, rec in self._claims.items():
                if other_uid != uid and wanted.intersection(rec.all_keys):
                    return True
            for other_uid, rec in self._reserved.items():
                if other_uid != uid and wanted.intersection(rec.keys):
                    return True
            return False

    # -- internals (call with _mu held) ------------------------------------

    def _forget(self, uid: str) -> None:
        with self._mu:
            self._remove_locked(uid)
            self._release_locked(uid)
            self._tombstones[uid] = None
            while len(self._tombstones) > 4096:
                self._tombstones.popitem(last=False)

    def _remove_locked(self, uid: str) -> None:
        rec = self._claims.pop(uid, None)
        if rec is not None:
            self._apply_locked(rec, -1)

    def _release_locked(self, uid: str) -> None:
        rec = self._reserved.pop(uid, None)
        if rec is not None:
            for key in rec.keys:
                if self._reserved_keys.get(key) == uid:
                    del self._reserved_keys[key]
            self._apply_locked(rec, -1)

    def _apply_locked(self, rec: _ClaimRecord, sign: int) -> None:
        if self._snap_shared:
            # a snapshot pins the current dicts: clone before the first
            # mutation (O(held devices), not O(fleet)) so the pinned
            # views stay frozen
            self._taken = dict(self._taken)
            self._usage = dict(self._usage)
            self._snap_shared = False
            _CLONES["ledger"].inc()
        for key in rec.keys:
            n = self._taken.get(key, 0) + sign
            if n > 0:
                self._taken[key] = n
            else:
                self._taken.pop(key, None)
        for ck, amount in rec.counters.items():
            n = self._usage.get(ck, 0) + sign * amount
            if n > 0:
                self._usage[ck] = n
            else:
                self._usage.pop(ck, None)
