"""Dependency-free distributed tracing for the claim lifecycle.

The BASELINE north-star metric — ResourceClaim-to-ready latency — is only
an aggregate histogram (``dra_allocation_seconds``,
``dra_prepare_batch_phase_seconds``); when one claim out of 512 is slow
there is no way to see *which* phase ate the time. The reference driver
answers that question with klog V(6) breadcrumbs plus component-base
pprof (cmd/compute-domain-controller/main.go:372-419); this module
answers it with an end-to-end, cross-process trace of every claim:
OpenTelemetry-style spans, W3C-``traceparent``-style context propagated
through a claim annotation, and a bounded in-memory flight recorder
exported as JSON at ``/debug/traces`` on the existing
:class:`~tpu_dra_driver.pkg.metrics.DebugHTTPServer`.

Design constraints, in priority order (mirroring
:mod:`tpu_dra_driver.pkg.faultinject`):

1. **Zero overhead when disabled.** Production code calls
   :func:`start_span` / :func:`span` / :func:`add_event` on hot paths
   (every prepare, every allocation). Disabled, each is ONE
   module-global bool check and a return of a shared no-op singleton —
   no allocation, no contextvar touch, no lock. Pinned by a microbench
   assertion in tests/test_tracing.py and recorded by bench.py under
   the ``observability`` key.
2. **Cross-process.** A :class:`SpanContext` serializes to the W3C
   ``traceparent`` wire form (``00-<trace_id>-<span_id>-<flags>``) and
   rides the ``resource.tpu.google.com/traceparent`` claim/CD
   annotation: the allocation controller opens the root span and stamps
   the annotation; the kubelet plugins parse it back and attach their
   spans to the same trace in a different process.
3. **Bounded.** Finished spans land in a :class:`FlightRecorder` — a
   capped deque; old traces fall off, the recorder can never grow
   without bound. Span events are capped per span.
4. **Modes.** ``disabled`` (default), ``sampled`` (root spans sampled
   at ``sample_ratio``; children inherit the parent's decision via the
   traceparent flags byte), ``always``.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

#: Annotation carrying the trace context across process boundaries
#: (claims are stamped by the allocator at commit; ComputeDomains by the
#: controller alongside the finalizer).
TRACEPARENT_ANNOTATION = "resource.tpu.google.com/traceparent"

#: W3C traceparent version byte; flags 01 = sampled.
_VERSION = "00"

#: Cap on events recorded per span (retry loops can attempt hundreds of
#: times against a slow rendezvous; the first N tell the story).
MAX_EVENTS_PER_SPAN = 64

_TRACE_RNG = random.Random()


class SpanContext:
    """Immutable (trace_id, span_id, sampled) triple — the wire identity."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"{_VERSION}-{self.trace_id}-{self.span_id}-{flags}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"SpanContext({self.traceparent()})"


def _new_trace_id() -> str:
    return f"{_TRACE_RNG.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_TRACE_RNG.getrandbits(64):016x}"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """``00-<32 hex>-<16 hex>-<2 hex>`` → SpanContext, or None on any
    malformed input (propagation is best-effort: a mangled annotation
    must never break a prepare)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
        flag_bits = int(flags, 16)
    except ValueError:
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return SpanContext(trace_id, span_id, sampled=bool(flag_bits & 0x01))


def from_object(obj: Optional[Dict]) -> Optional[SpanContext]:
    """Read the traceparent annotation off a k8s object dict."""
    if not obj:
        return None
    annotations = ((obj.get("metadata") or {}).get("annotations") or {})
    return parse_traceparent(annotations.get(TRACEPARENT_ANNOTATION))


def annotate(obj: Dict, ctx: Optional[SpanContext]) -> None:
    """Stamp ``ctx`` onto a k8s object dict (no-op for a None context)."""
    if ctx is None:
        return
    meta = obj.setdefault("metadata", {})
    annotations = meta.setdefault("annotations", {})
    annotations[TRACEPARENT_ANNOTATION] = ctx.traceparent()


class Span:
    """One recorded operation. Context-manager: exceptions mark the span
    failed and propagate. ``end()`` is idempotent and hands the span to
    the process flight recorder."""

    __slots__ = ("name", "context", "parent_span_id", "start_unix",
                 "end_unix", "attributes", "events", "status", "_t0",
                 "_ended")

    recording = True

    def __init__(self, name: str, context: SpanContext,
                 parent_span_id: Optional[str] = None,
                 attributes: Optional[Dict] = None):
        self.name = name
        self.context = context
        self.parent_span_id = parent_span_id
        self.start_unix = time.time()
        self.end_unix: Optional[float] = None
        self.attributes: Dict = dict(attributes or {})
        self.events: List[Dict] = []
        self.status = "unset"
        self._t0 = time.perf_counter()
        self._ended = False

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attributes) -> None:
        if len(self.events) >= MAX_EVENTS_PER_SPAN:
            if len(self.events) == MAX_EVENTS_PER_SPAN:
                self.events.append({"ts": time.time(), "name": "truncated",
                                    "attributes": {}})
            return
        self.events.append({"ts": time.time(), "name": name,
                            "attributes": attributes})

    def end(self, status: Optional[str] = None) -> None:
        if self._ended:
            return
        self._ended = True
        if status is not None:
            self.status = status
        elif self.status == "unset":
            self.status = "ok"
        self.end_unix = self.start_unix + (time.perf_counter() - self._t0)
        _RECORDER.record(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.set_attribute("error", f"{exc_type.__name__}: {exc}")
            self.end(status="error")
        else:
            self.end()
        return False

    def to_dict(self) -> Dict:
        return {
            "name": self.name,
            "trace_id": self.context.trace_id,
            "span_id": self.context.span_id,
            "parent_span_id": self.parent_span_id,
            "start_unix": round(self.start_unix, 6),
            "end_unix": (round(self.end_unix, 6)
                         if self.end_unix is not None else None),
            "duration_ms": (round((self.end_unix - self.start_unix) * 1e3, 3)
                            if self.end_unix is not None else None),
            "status": self.status,
            "attributes": self.attributes,
            "events": self.events,
            "process": _SERVICE,
        }


class _NoopSpan:
    """Shared do-nothing span: the disabled/unsampled fast path returns
    this singleton so hot paths never allocate."""

    __slots__ = ()
    recording = False
    context = None
    name = ""

    def set_attribute(self, key, value):
        pass

    def add_event(self, name, **attributes):
        pass

    def end(self, status=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class FlightRecorder:
    """Bounded in-memory store of finished spans, queryable by trace.

    Eviction is NOT silent: every span pushed out of the full deque
    counts in :attr:`evicted`, a trace whose LAST retained span is
    pushed out counts in :attr:`evicted_traces` and in the
    ``dra_traces_evicted_total`` metric (trace units, as the name
    says), and the critical-path aggregator (pkg/criticalpath.py)
    reports both as coverage — attribution computed over a recorder
    that quietly dropped half its traffic must say so."""

    def __init__(self, capacity: int = 2048):
        self._mu = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        #: spans pushed out of the full deque
        self.evicted = 0
        #: traces whose every span has been pushed out
        self.evicted_traces = 0
        # trace_id -> retained span count (drops to 0 = trace evicted)
        self._trace_counts: Dict[str, int] = {}

    def record(self, span: Span) -> None:
        trace_evicted = False
        with self._mu:
            if self._spans.maxlen and len(self._spans) == self._spans.maxlen:
                old_tid = self._spans[0].context.trace_id
                self.evicted += 1
                left = self._trace_counts.get(old_tid, 1) - 1
                if left <= 0:
                    self._trace_counts.pop(old_tid, None)
                    self.evicted_traces += 1
                    trace_evicted = True
                else:
                    self._trace_counts[old_tid] = left
            tid = span.context.trace_id
            self._trace_counts[tid] = self._trace_counts.get(tid, 0) + 1
            self._spans.append(span)
        _count_recorded(evicted_trace=trace_evicted)

    def __len__(self) -> int:
        with self._mu:
            return len(self._spans)

    def clear(self) -> None:
        with self._mu:
            self._spans.clear()
            self._trace_counts.clear()
            self.evicted = 0
            self.evicted_traces = 0

    def trace(self, trace_id: str) -> List[Dict]:
        """Every retained finished span of one trace, oldest first."""
        with self._mu:
            return [s.to_dict() for s in self._spans
                    if s.context.trace_id == trace_id]

    def all_spans(self) -> List[Dict]:
        """Every retained finished span, oldest first — one pass for
        the critical-path aggregator (grouping per-trace through
        :meth:`trace` would rescan the deque per trace)."""
        with self._mu:
            return [s.to_dict() for s in self._spans]

    def traces(self) -> List[Dict]:
        """Per-trace summaries, most recent first."""
        with self._mu:
            spans = list(self._spans)
        by_trace: Dict[str, Dict] = {}
        for s in spans:
            tid = s.context.trace_id
            row = by_trace.setdefault(tid, {
                "trace_id": tid, "spans": 0, "root": None,
                "start_unix": s.start_unix, "end_unix": s.end_unix,
                "errors": 0,
            })
            row["spans"] += 1
            row["start_unix"] = min(row["start_unix"], s.start_unix)
            if s.end_unix is not None:
                row["end_unix"] = max(row["end_unix"] or 0, s.end_unix)
            if s.parent_span_id is None:
                row["root"] = s.name
            if s.status == "error":
                row["errors"] += 1
        out = []
        for row in by_trace.values():
            if row["end_unix"] is not None:
                row["duration_ms"] = round(
                    (row["end_unix"] - row["start_unix"]) * 1e3, 3)
            out.append(row)
        out.sort(key=lambda r: r["start_unix"], reverse=True)
        return out


#: Module-global fast-path flag: False means every API here returns
#: immediately (the production default — tracing is opt-in via
#: ``--trace-mode``).
_ENABLED = False
_MODE = "disabled"
_RATIO = 0.01
_SERVICE = ""
_RECORDER = FlightRecorder()
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_dra_current_span", default=None)


def configure(mode: str = "disabled", sample_ratio: float = 0.01,
              service: str = "", capacity: Optional[int] = None) -> None:
    """Arm the subsystem. ``mode``: disabled | sampled | always."""
    global _ENABLED, _MODE, _RATIO, _SERVICE, _RECORDER
    if mode not in ("disabled", "sampled", "always"):
        raise ValueError(f"trace mode {mode!r}: expected disabled|sampled|"
                         f"always")
    _MODE = mode
    _RATIO = max(0.0, min(1.0, sample_ratio))
    if service:
        _SERVICE = service
    if capacity is not None:
        _RECORDER = FlightRecorder(capacity)
    _ENABLED = mode != "disabled"


def enabled() -> bool:
    return _ENABLED


def mode() -> str:
    return _MODE


def recorder() -> FlightRecorder:
    return _RECORDER


def reset() -> None:
    """Test helper: disable and drop recorded spans."""
    global _ENABLED, _MODE, _SERVICE
    _ENABLED = False
    _MODE = "disabled"
    _SERVICE = ""
    _RECORDER.clear()
    _CURRENT.set(None)


def _sample_root() -> bool:
    if _MODE == "always":
        return True
    if _MODE == "sampled":
        return _TRACE_RNG.random() < _RATIO
    return False


def start_span(name: str, parent=None, attributes: Optional[Dict] = None):
    """Open a span. ``parent`` is a Span, SpanContext, or None (a new
    root). Returns :data:`NOOP_SPAN` when tracing is disabled or the
    sampling decision (root: by mode; child: inherited from the parent)
    says no."""
    if not _ENABLED:
        return NOOP_SPAN
    parent_ctx: Optional[SpanContext]
    if parent is None:
        parent_ctx = None
    elif isinstance(parent, SpanContext):
        parent_ctx = parent
    elif isinstance(parent, Span):
        parent_ctx = parent.context
    else:
        parent_ctx = None
    if parent_ctx is not None:
        if not parent_ctx.sampled and _MODE != "always":
            return NOOP_SPAN
        ctx = SpanContext(parent_ctx.trace_id, _new_span_id(), sampled=True)
        return Span(name, ctx, parent_span_id=parent_ctx.span_id,
                    attributes=attributes)
    if not _sample_root():
        return NOOP_SPAN
    ctx = SpanContext(_new_trace_id(), _new_span_id(), sampled=True)
    return Span(name, ctx, parent_span_id=None, attributes=attributes)


class _UseSpan:
    """Context manager installing a span as the implicit current span
    (the parent for :func:`span` children and the source of log/exemplar
    correlation). Accepts None / non-recording spans as a no-op."""

    __slots__ = ("_span", "_token")

    def __init__(self, span):
        self._span = span
        self._token = None

    def __enter__(self):
        if self._span is not None and self._span.recording:
            self._token = _CURRENT.set(self._span)
        return self._span

    def __exit__(self, *exc):
        if self._token is not None:
            _CURRENT.reset(self._token)
        return False


def use_span(span) -> _UseSpan:
    return _USE_NOOP if not _ENABLED else _UseSpan(span)


_USE_NOOP = _UseSpan(None)


class _ChildScope:
    """``with tracing.span("phase"):`` — a child of the current span that
    is also installed as current for its duration."""

    __slots__ = ("_span", "_token")

    def __init__(self, span):
        self._span = span
        self._token = None

    def __enter__(self):
        if self._span.recording:
            self._token = _CURRENT.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _CURRENT.reset(self._token)
        self._span.__exit__(exc_type, exc, tb)
        return False


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return NOOP_SPAN

    def __exit__(self, *exc):
        return False


_NOOP_SCOPE = _NoopScope()


def span(name: str, attributes: Optional[Dict] = None,
         root: bool = False):
    """Child-of-current span scope. Without a recording current span this
    is a no-op unless ``root=True`` (which opens a fresh root trace,
    subject to the sampling mode)."""
    if not _ENABLED:
        return _NOOP_SCOPE
    cur = _CURRENT.get()
    if cur is None or not cur.recording:
        if not root:
            return _NOOP_SCOPE
        s = start_span(name, parent=None, attributes=attributes)
    else:
        s = start_span(name, parent=cur, attributes=attributes)
    if not s.recording:
        return _NOOP_SCOPE
    return _ChildScope(s)


def current_span():
    """The innermost recording span, or None."""
    if not _ENABLED:
        return None
    cur = _CURRENT.get()
    return cur if (cur is not None and cur.recording) else None


def current_context() -> Optional[SpanContext]:
    cur = current_span()
    return cur.context if cur is not None else None


def add_event(name: str, **attributes) -> None:
    """Record an event on the current span (used by e.g. the
    fault-injection subsystem so every injected fault shows up inside
    the trace of the claim it hit). Disabled: one bool check."""
    if not _ENABLED:
        return
    cur = _CURRENT.get()
    if cur is not None and cur.recording:
        cur.add_event(name, **attributes)


def exemplar(span_or_ctx=None) -> Optional[Dict[str, str]]:
    """Prometheus exemplar labels for a span/context (default: the
    current span) — attach to histogram observations so a latency bucket
    links back to a concrete trace. None when not tracing."""
    if not _ENABLED:
        return None
    if span_or_ctx is None:
        ctx = current_context()
    elif isinstance(span_or_ctx, SpanContext):
        ctx = span_or_ctx
    else:
        ctx = getattr(span_or_ctx, "context", None)
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


def _count_recorded(evicted_trace: bool = False) -> None:
    # lazy import mirrors faultinject._count_fired: the disabled path
    # stays import-free, and metrics never imports tracing at module load
    from tpu_dra_driver.pkg import metrics as _metrics
    _metrics.TRACE_SPANS_RECORDED.inc()
    if evicted_trace:
        _metrics.TRACES_EVICTED.inc()
