"""Feature-gate registry with cross-gate dependency validation.

Reference analog: pkg/featuregates/featuregates.go:32-189 — a
component-base featuregate registry versioned against the project version,
with gates and *mutual-exclusion* validation (DynamicMIG cannot be combined
with Passthrough / health check / MPS).

TPU mapping of the reference gates:

=========================  =================================  =======
reference gate             TPU gate                           default
=========================  =================================  =======
TimeSlicingSettings        TimeSlicingSettings                False
MPSSupport                 MultiProcessSharing                False
IMEXDaemonsWithDNSNames    SliceDaemonsWithDNSNames           True
PassthroughSupport         PassthroughSupport                 False
NVMLDeviceHealthCheck      DeviceHealthCheck                  False
DynamicMIG                 DynamicSubslice                    False
ComputeDomainCliques       ComputeDomainCliques               True
CrashOnNVLinkFabricErrors  CrashOnICIFabricErrors             True
=========================  =================================  =======
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Mapping


class Stage(Enum):
    ALPHA = "Alpha"
    BETA = "Beta"
    GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    name: str
    default: bool
    stage: Stage
    locked: bool = False  # locked-to-default (GA'd) gates cannot be changed


TIME_SLICING_SETTINGS = "TimeSlicingSettings"
MULTI_PROCESS_SHARING = "MultiProcessSharing"
SLICE_DAEMONS_WITH_DNS_NAMES = "SliceDaemonsWithDNSNames"
PASSTHROUGH_SUPPORT = "PassthroughSupport"
DEVICE_HEALTH_CHECK = "DeviceHealthCheck"
DYNAMIC_SUBSLICE = "DynamicSubslice"
COMPUTE_DOMAIN_CLIQUES = "ComputeDomainCliques"
CRASH_ON_ICI_FABRIC_ERRORS = "CrashOnICIFabricErrors"
#: advertise *creatable* sub-slice profile slots (placement picked by the
#: kubelet plugin at prepare time — the DynamicMIG profile-advertising
#: model); requires DynamicSubslice for the partition machinery.
DYNAMIC_REPARTITION = "DynamicRepartition"
#: advertise per-chip multi-process client SEATS as allocatable devices —
#: the claim-per-request serving tier (one small claim = one bounded
#: client on a shared chip). Unlike MultiProcessSharing (one claim whose
#: own processes share its chip), seats admit MANY claims per chip, so
#: this gate composes with DynamicRepartition: per-chip exclusion between
#: seats and partitions is enforced dynamically by the repartition state
#: machine and the KEP-4815 counter model, not by a static gate conflict.
SHARED_CHIP_SERVING = "SharedChipServing"
#: persist prepared-claim state as an append-only CRC-framed journal over
#: a compacted base instead of rewriting the whole checkpoint file per
#: transition, with a single group-commit writer thread coalescing fsyncs
#: across concurrent NodePrepareResources batches. Off = the rewrite
#: (dual-version envelope) format; the two formats migrate in both
#: directions at manager construction, so the gate can flip per restart.
JOURNAL_CHECKPOINT = "JournalCheckpoint"

_SPECS: tuple[FeatureSpec, ...] = (
    FeatureSpec(TIME_SLICING_SETTINGS, False, Stage.ALPHA),
    FeatureSpec(MULTI_PROCESS_SHARING, False, Stage.ALPHA),
    FeatureSpec(SLICE_DAEMONS_WITH_DNS_NAMES, True, Stage.BETA),
    FeatureSpec(PASSTHROUGH_SUPPORT, False, Stage.ALPHA),
    FeatureSpec(DEVICE_HEALTH_CHECK, False, Stage.ALPHA),
    FeatureSpec(DYNAMIC_SUBSLICE, False, Stage.ALPHA),
    FeatureSpec(COMPUTE_DOMAIN_CLIQUES, True, Stage.BETA),
    FeatureSpec(CRASH_ON_ICI_FABRIC_ERRORS, True, Stage.BETA),
    FeatureSpec(DYNAMIC_REPARTITION, False, Stage.ALPHA),
    FeatureSpec(SHARED_CHIP_SERVING, False, Stage.ALPHA),
    FeatureSpec(JOURNAL_CHECKPOINT, False, Stage.ALPHA),
)

# Mutual exclusions (reference featuregates.go:170-189): dynamic
# repartitioning owns the chip exclusively, so passthrough flips, health
# monitoring of fixed placements, and multi-process share daemons conflict.
_MUTUALLY_EXCLUSIVE: tuple[tuple[str, str], ...] = (
    (DYNAMIC_SUBSLICE, PASSTHROUGH_SUPPORT),
    (DYNAMIC_SUBSLICE, DEVICE_HEALTH_CHECK),
    (DYNAMIC_SUBSLICE, MULTI_PROCESS_SHARING),
)


class FeatureGateError(ValueError):
    pass


@dataclass
class FeatureGates:
    """A resolved set of feature gates."""

    _values: Dict[str, bool] = field(default_factory=dict)

    def __post_init__(self):
        for spec in _SPECS:
            self._values.setdefault(spec.name, spec.default)

    @staticmethod
    def known() -> Mapping[str, FeatureSpec]:
        return {s.name: s for s in _SPECS}

    def enabled(self, name: str) -> bool:
        if name not in self._values:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        return self._values[name]

    def set(self, name: str, value: bool) -> None:
        spec = self.known().get(name)
        if spec is None:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        if spec.locked and value != spec.default:
            raise FeatureGateError(f"feature gate {name!r} is locked to {spec.default}")
        self._values[name] = value

    def apply(self, overrides: Mapping[str, bool]) -> None:
        # Validate a merged copy before committing, so a rejected override
        # set cannot leave this object in a mutually-exclusive state.
        trial = FeatureGates(dict(self._values))
        for k, v in overrides.items():
            trial.set(k, v)
        trial.validate()
        self._values = trial._values

    def parse(self, spec: str) -> None:
        """Parse a ``Gate1=true,Gate2=false`` string (the FEATURE_GATES env
        flag format, reference pkg/flags/featuregates.go)."""
        overrides: Dict[str, bool] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise FeatureGateError(
                    f"malformed feature gate {part!r}: expected Name=true|false"
                )
            name, _, raw = part.partition("=")
            raw = raw.strip().lower()
            if raw not in ("true", "false"):
                raise FeatureGateError(
                    f"malformed feature gate value {part!r}: expected true or false"
                )
            overrides[name.strip()] = raw == "true"
        self.apply(overrides)

    def validate(self) -> None:
        for a, b in _MUTUALLY_EXCLUSIVE:
            if self._values.get(a) and self._values.get(b):
                raise FeatureGateError(
                    f"feature gates {a!r} and {b!r} are mutually exclusive"
                )

    def as_dict(self) -> Dict[str, bool]:
        return dict(self._values)


def from_env_spec(spec: str | None) -> FeatureGates:
    fg = FeatureGates()
    if spec:
        fg.parse(spec)
    fg.validate()
    return fg
