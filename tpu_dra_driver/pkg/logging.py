"""Structured, correlated logging for every driver binary.

Reference analog: klog's ``-v`` verbosity plus the JSON logging format
of component-base (``--logging-format=json``). All five ``cmd/*``
entrypoints route through :func:`setup` (via
``pkg/flags.setup_logging``), so one ``--log-format {text,json}`` flag
switches the whole process.

JSON records carry correlation fields so one ``jq`` filter follows one
claim across binaries:

- static process identity (``component``, ``node``) set once at startup;
- per-scope fields (``claim``, ``claim_uid``, ``cd``) pushed with
  :func:`fields` around a unit of work (contextvar-scoped, so concurrent
  gRPC handler threads never bleed into each other);
- ``trace_id``/``span_id`` of the current tracing span
  (:mod:`tpu_dra_driver.pkg.tracing`) whenever a span is active — the
  log line and the flight-recorder trace share a key.

Text mode keeps the historical klog-ish one-liner format unchanged.
"""

from __future__ import annotations

import contextvars
import json
import logging as _logging
import sys
import time
from contextlib import contextmanager
from typing import Dict

TEXT_FORMAT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"

#: process-wide identity merged into every JSON record
_STATIC: Dict[str, str] = {}

_FIELDS: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_dra_log_fields", default=None)


class JsonFormatter(_logging.Formatter):
    """One JSON object per line: ts/level/logger/msg + correlation."""

    def format(self, record: _logging.LogRecord) -> str:
        out: Dict[str, object] = {
            "ts": round(record.created, 3),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S",
                                  time.gmtime(record.created))
                    + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        out.update(_STATIC)
        scoped = _FIELDS.get()
        if scoped:
            out.update(scoped)
        from tpu_dra_driver.pkg import tracing
        span = tracing.current_span()
        if span is not None:
            out["trace_id"] = span.context.trace_id
            out["span_id"] = span.context.span_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        try:
            return json.dumps(out, default=str)
        except (TypeError, ValueError):  # unserializable arg: degrade, never drop
            out["msg"] = repr(out.get("msg"))
            return json.dumps({k: str(v) for k, v in out.items()})


def level_for(verbosity: int) -> int:
    """klog-style ``-v`` 0-7 → stdlib level (same mapping the repo has
    always used)."""
    if verbosity >= 6:
        return _logging.DEBUG
    if verbosity >= 2:
        return _logging.INFO
    return _logging.WARNING


def setup(verbosity: int, log_format: str = "text", component: str = "",
          node: str = "") -> None:
    """(Re)configure the root logger. ``log_format``: text | json."""
    if log_format not in ("text", "json"):
        raise SystemExit(
            f"--log-format: expected text or json, got {log_format!r}")
    if component:
        _STATIC["component"] = component
    if node:
        _STATIC["node"] = node
    handler = _logging.StreamHandler(sys.stderr)
    if log_format == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(_logging.Formatter(TEXT_FORMAT))
    root = _logging.getLogger()
    root.setLevel(level_for(verbosity))
    root.handlers[:] = [handler]


def set_static(**kw: str) -> None:
    """Merge process-identity fields (e.g. node name learned after flag
    parsing) into every subsequent JSON record."""
    _STATIC.update({k: v for k, v in kw.items() if v})


@contextmanager
def fields(**kw):
    """Scope correlation fields (claim, cd, ...) over a unit of work;
    contextvar-backed so concurrent handler threads stay isolated."""
    current = _FIELDS.get() or {}
    token = _FIELDS.set({**current, **kw})
    try:
        yield
    finally:
        _FIELDS.reset(token)
