"""Flag plumbing with environment-variable mirrors.

Reference analog: the urfave/cli setup in cmd/*/main.go — every flag has
an env-var mirror (e.g. ``--node-name`` / ``NODE_NAME``,
``gpu-kubelet-plugin/main.go:83-166``) so Helm can configure pods purely
through env, plus the ``FEATURE_GATES`` env flag (pkg/flags/featuregates.go).
"""

from __future__ import annotations

import argparse
import os
import time
from typing import Any, Dict, Optional

from tpu_dra_driver.pkg.featuregates import FeatureGates, from_env_spec


class EnvArgumentParser(argparse.ArgumentParser):
    """add_argument(..., env="NODE_NAME") uses the env var as the default
    (explicit CLI value still wins)."""

    def add_argument(self, *args, env: Optional[str] = None, **kwargs):  # type: ignore[override]
        if env is not None:
            env_val = os.environ.get(env)
            if env_val is not None:
                typ = kwargs.get("type")
                if kwargs.get("action") in ("store_true", "store_false"):
                    kwargs["default"] = env_val.lower() in ("1", "true", "yes")
                else:
                    kwargs["default"] = typ(env_val) if typ else env_val
            help_text = kwargs.get("help", "")
            kwargs["help"] = f"{help_text} [env: {env}]".strip()
        return super().add_argument(*args, **kwargs)


def add_common_flags(parser: EnvArgumentParser) -> None:
    parser.add_argument("--feature-gates", env="FEATURE_GATES", default="",
                        help="comma-separated Gate=true|false overrides")
    parser.add_argument("-v", "--verbosity", env="LOG_VERBOSITY", type=int,
                        default=4, help="log verbosity (klog-style 0-7)")
    parser.add_argument("--log-format", env="LOG_FORMAT", default="text",
                        choices=["text", "json"],
                        help="text = klog-style one-liners; json = one "
                             "JSON object per line with trace/claim/node "
                             "correlation fields (pkg/logging.py)")
    parser.add_argument("--trace-mode", env="TRACE_MODE", default="disabled",
                        choices=["disabled", "sampled", "always"],
                        help="claim-lifecycle tracing (pkg/tracing.py): "
                             "spans land in the in-process flight "
                             "recorder served at /debug/traces; disabled "
                             "costs one bool check per span site")
    parser.add_argument("--trace-sample-ratio", env="TRACE_SAMPLE_RATIO",
                        type=float, default=0.01,
                        help="root-span sampling probability for "
                             "--trace-mode=sampled")
    parser.add_argument("--slo-tick", env="SLO_TICK", type=float,
                        default=10.0,
                        help="SLO engine evaluation interval in seconds "
                             "(pkg/slo.py: burn-rate gauges, /debug/slo, "
                             "SLOBurnRate Events); 0 disables the engine")
    parser.add_argument("--timeseries-interval", env="TIMESERIES_INTERVAL",
                        type=float, default=5.0,
                        help="sampling interval in seconds for the "
                             "in-process time-series ring (pkg/metrics "
                             "TimeSeriesRing: periodic registry snapshot "
                             "deltas + recording rules, served at "
                             "/debug/timeseries); 0 disables the ring")
    parser.add_argument("--timeseries-capacity", env="TIMESERIES_CAPACITY",
                        type=int, default=360,
                        help="points retained per series in the "
                             "time-series ring (360 x 5s = 30 min)")
    parser.add_argument("--slo-windows", env="SLO_WINDOWS", default="",
                        help="burn-rate windows as "
                             "name:long/short:threshold[,...] in seconds "
                             "(e.g. fast:3600/300:14.4,slow:21600/1800:6); "
                             "empty = the Google-SRE-style defaults")
    parser.add_argument("--kube-api-qps", env="KUBE_API_QPS", type=float,
                        default=50.0)
    parser.add_argument("--kubeconfig", env="KUBECONFIG", default="",
                        help="out-of-cluster kubeconfig path")
    parser.add_argument("--kube-backend", env="KUBE_BACKEND", default="rest",
                        choices=["rest", "fake"],
                        help="fake = per-process in-memory API server for "
                             "single-binary smoke tests (state is NOT "
                             "shared between processes; for a multi-"
                             "component hardware-free demo use "
                             "demo/run_e2e_demo.py, which drives all "
                             "components in one process)")


def parse_gates(args: argparse.Namespace) -> FeatureGates:
    return from_env_spec(getattr(args, "feature_gates", "") or None)


def setup_logging(verbosity: int, log_format: str = "text",
                  component: str = "", node: str = "") -> None:
    from tpu_dra_driver.pkg import logging as dralog
    dralog.setup(verbosity, log_format=log_format, component=component,
                 node=node)


def parse_slo_windows(spec: str):
    """``name:long/short:threshold[,...]`` → tuple of
    :class:`~tpu_dra_driver.pkg.slo.BurnWindow`; '' → the defaults.
    Raises SystemExit with the offending clause on malformed input (a
    typo'd window must not silently fall back to defaults)."""
    from tpu_dra_driver.pkg.slo import DEFAULT_WINDOWS, BurnWindow
    if not spec.strip():
        return DEFAULT_WINDOWS
    out = []
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        try:
            name, ranges, threshold = clause.split(":")
            long_s, short_s = ranges.split("/")
            window = BurnWindow(name, float(long_s), float(short_s),
                                float(threshold))
            if window.long_s <= 0 or window.short_s <= 0 \
                    or window.short_s > window.long_s:
                raise ValueError("short must be 0 < short <= long")
        except ValueError as e:
            raise SystemExit(
                f"--slo-windows: clause {clause!r}: expected "
                f"name:long/short:threshold ({e})")
        out.append(window)
    return tuple(out)


def setup_observability(args: argparse.Namespace, component: str) -> None:
    """The one call every cmd/* entrypoint makes after parsing flags:
    structured logging (--log-format/-v), claim-lifecycle tracing
    (--trace-mode/--trace-sample-ratio), and the SLO engine
    (--slo-tick/--slo-windows: dra_slo_* gauges + /debug/slo; binaries
    attach their EventRecorder later via ``slo.attach_recorder`` once
    API clients exist), all wired to the common flag set from
    :func:`add_common_flags`."""
    setup_logging(getattr(args, "verbosity", 4),
                  getattr(args, "log_format", "text"),
                  component=component,
                  node=getattr(args, "node_name", ""))
    from tpu_dra_driver.pkg import tracing
    tracing.configure(getattr(args, "trace_mode", "disabled"),
                      sample_ratio=getattr(args, "trace_sample_ratio", 0.01),
                      service=component)
    from tpu_dra_driver.pkg import slo
    # absent attribute = the caller never opted in (bare test Namespaces,
    # library embedders): NO engine thread. The cmd binaries always have
    # the flag (default 10.0), so production still gets the engine.
    tick = getattr(args, "slo_tick", 0.0)
    if tick and tick > 0:
        engine = slo.SLOEngine(
            windows=parse_slo_windows(getattr(args, "slo_windows", "")),
            tick=tick, component=component)
        slo.configure(engine)
        engine.start()
    else:
        slo.configure(None)
    # in-process time-series ring (--timeseries-interval/-capacity):
    # same opt-in shape as the SLO engine — absent attribute or 0 means
    # no sampler thread (the ring reads the registry; hot paths never
    # see it either way)
    from tpu_dra_driver.pkg import metrics
    ts_interval = getattr(args, "timeseries_interval", 0.0)
    if ts_interval and ts_interval > 0:
        metrics.timeseries_configure(
            interval=ts_interval,
            capacity=getattr(args, "timeseries_capacity", 360))
    else:
        metrics.timeseries_reset()


_PROCESS_START_UNIX = time.time()


def debug_vars_fn(args: argparse.Namespace, component: str):
    """The ``/debug/vars`` provider every binary hands its
    DebugHTTPServer: build info, uptime, the parsed flag set, trace
    mode, and fault-point arm state — the first page of a doctor
    bundle."""

    def vars_() -> Dict[str, Any]:
        from tpu_dra_driver import __version__
        from tpu_dra_driver.pkg import faultinject, tracing
        return {
            "component": component,
            "version": __version__,
            "pid": os.getpid(),
            "start_unix": round(_PROCESS_START_UNIX, 3),
            "uptime_s": round(time.time() - _PROCESS_START_UNIX, 3),
            "flags": config_dict(args),
            "trace_mode": tracing.mode(),
            "faults_armed": faultinject.armed(),
            "fault_points_armed": faultinject.armed_points(),
        }
    return vars_


def config_dict(args: argparse.Namespace) -> Dict[str, Any]:
    return dict(sorted(vars(args).items()))


def parse_http_endpoint(value: str):
    """``host:port`` / ``:port`` / ``[v6]:port`` → (host, port); '' → None.

    Raises SystemExit with a clear message on malformed values (a raw
    ValueError traceback would crash-loop the pod with no hint)."""
    if not value:
        return None
    host, sep, port = value.strip().rpartition(":")
    if host.startswith("[") and host.endswith("]"):  # [::]:8080
        host = host[1:-1]
    if not sep or not port.isdigit():
        raise SystemExit(
            f"--http-endpoint: expected host:port or :port, got {value!r}")
    return (host or "0.0.0.0", int(port))
