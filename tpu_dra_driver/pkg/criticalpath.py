"""Critical-path analysis: attribute claim latency to named segments.

The flight recorder (pkg/tracing.py) answers "what happened to THIS
claim" as raw spans; this module answers the operator question one
level up: **where did the time go?** Each finished trace is walked into
a per-segment attribution — allocation pick/commit, commit-conflict
retries, each kubelet prepare phase, the cd.await_ready rendezvous
wait, the scheduler/kubelet gap between allocation and prepare — and
rolling per-segment p50/p99 aggregates are served at
``/debug/criticalpath`` (per-trace attribution at
``/debug/criticalpath/<trace-id>``) on every
:class:`~tpu_dra_driver.pkg.metrics.DebugHTTPServer`.

Attribution model: a span's segment is charged its **self time** —
wall duration minus the union of its children's intervals (children
clipped to the parent, overlapping children merged, so a parent that
runs two children concurrently is not charged negative time). Gaps the
spans don't cover are reported honestly: ``queue.wait`` (allocation
root end → first prepare span start: the scheduler/kubelet window the
driver does not control) and ``unattributed`` (end-to-end minus
everything accounted). Coverage is equally honest: the aggregate
report carries the flight recorder's eviction count
(``dra_traces_evicted_total``) so attribution over a recorder that
dropped traces says so instead of silently narrowing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

#: span name -> segment name. Unknown span names fall through to their
#: own name, so new instrumentation shows up without a mapping edit.
SEGMENT_BY_SPAN = {
    "allocator.allocate": "allocation",
    "allocator.pick": "allocation.pick",
    "allocator.commit": "allocation.commit",
    "allocator.commit.verify_read": "allocation.commit.verify_read",
    "allocator.commit.status_write": "allocation.commit.status_write",
    "allocator.commit.reserve_phase1": "allocation.commit.reserve_phase1",
    "allocator.commit.await_grants": "allocation.commit.await_grants",
    "allocator.commit.phase2_graduate": "allocation.commit.phase2_graduate",
    "allocator.commit.unwind": "allocation.commit.unwind",
    "kubelet.prepare": "prepare",
    "prepare.read_checkpoint": "prepare.read_checkpoint",
    "prepare.write_ahead": "prepare.write_ahead",
    "prepare.devices": "prepare.devices",
    "prepare.subslice": "prepare.subslice",
    "prepare.cdi": "prepare.cdi",
    "prepare.commit": "prepare.commit",
    "cd.prepare": "cd.prepare",
    "cd.await_ready": "cd.await_ready",
    "cd.write_ahead": "cd.write_ahead",
    "cd.cdi_write": "cd.cdi_write",
    "cd.commit": "cd.commit",
    "cd.rendezvous": "cd.rendezvous",
    "daemon.join": "daemon.join",
    "daemon.clique_render": "daemon.clique_render",
}

#: Span event names that mean "one retry happened here": cd.await_ready
#: retry attempts and allocator verify-on-commit conflicts.
RETRY_EVENT_NAMES = ("retry", "commit-conflict")

#: Spans whose START marks the end of the scheduler/kubelet queue wait.
_PREPARE_ROOTS = ("kubelet.prepare", "cd.prepare")


def _merged_intervals(ivs: List[Tuple[float, float]]
                      ) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for start, end in sorted(ivs):
        if out and start <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], end))
        else:
            out.append((start, end))
    return out


def _children_coverage(parent: Dict, children: List[Dict]) -> float:
    """Seconds of ``parent``'s interval covered by its children
    (children clipped to the parent; overlaps merged — two concurrent
    children cover a window once, not twice)."""
    p0, p1 = parent["start_unix"], parent["end_unix"]
    clipped = []
    for c in children:
        c0, c1 = max(c["start_unix"], p0), min(c["end_unix"], p1)
        if c1 > c0:
            clipped.append((c0, c1))
    return sum(e - s for s, e in _merged_intervals(clipped))


def analyze(spans: Sequence[Dict]) -> Dict:
    """Per-trace latency attribution from one trace's finished spans
    (the ``/debug/traces/<id>`` span dict shape). Tolerates partial
    traces — one process's half, missing CD phases, orphaned parents —
    because that is what a single component's recorder actually holds."""
    finished = [s for s in spans
                if s.get("end_unix") is not None
                and s.get("start_unix") is not None]
    if not finished:
        return {"trace_id": None, "spans": 0, "errors": 0, "e2e_ms": 0.0,
                "segments_ms": {}, "retries": {}, "dominant": None}
    by_id = {s["span_id"]: s for s in finished}
    children: Dict[str, List[Dict]] = {}
    for s in finished:
        parent = s.get("parent_span_id")
        if parent:
            children.setdefault(parent, []).append(s)

    t_min = min(s["start_unix"] for s in finished)
    t_max = max(s["end_unix"] for s in finished)
    e2e_s = t_max - t_min

    segments: Dict[str, float] = {}
    retries: Dict[str, int] = {}
    errors = 0
    for s in finished:
        if s.get("status") == "error":
            errors += 1
        segment = SEGMENT_BY_SPAN.get(s["name"], s["name"])
        self_s = (s["end_unix"] - s["start_unix"]) \
            - _children_coverage(s, children.get(s["span_id"], []))
        segments[segment] = segments.get(segment, 0.0) + max(0.0, self_s)
        n_retries = sum(1 for ev in s.get("events") or []
                        if ev.get("name") in RETRY_EVENT_NAMES)
        if n_retries:
            retries[segment] = retries.get(segment, 0) + n_retries

    # the scheduler/kubelet gap: allocation root committed, prepare not
    # yet called — time the driver does not control but operators see
    root = next((s for s in finished
                 if s["name"] == "allocator.allocate"), None)
    prepare_starts = [s["start_unix"] for s in finished
                      if s["name"] in _PREPARE_ROOTS]
    if root is not None and prepare_starts:
        gap = min(prepare_starts) - root["end_unix"]
        if gap > 0:
            segments["queue.wait"] = segments.get("queue.wait", 0.0) + gap

    attributed = sum(segments.values())
    if e2e_s - attributed > 1e-9:
        segments["unattributed"] = e2e_s - attributed

    segments_ms = {k: round(v * 1e3, 3) for k, v in segments.items()}
    dominant = max(segments_ms, key=segments_ms.get) if segments_ms else None
    root_span = next((s for s in finished
                      if not s.get("parent_span_id")
                      or s["parent_span_id"] not in by_id), finished[0])
    return {
        "trace_id": finished[0].get("trace_id"),
        "root": root_span["name"],
        "spans": len(finished),
        "errors": errors,
        "e2e_ms": round(e2e_s * 1e3, 3),
        "segments_ms": segments_ms,
        "retries": retries,
        "dominant": dominant,
    }


def _percentile(values: List[float], pct: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(len(vals) - 1, int(round(pct / 100.0 * (len(vals) - 1))))
    return vals[idx]


def aggregate(analyses: Sequence[Dict],
              coverage: Optional[Dict] = None) -> Dict:
    """Rolling per-segment aggregates over many per-trace analyses:
    p50/p99/mean/max per segment, end-to-end distribution, total retry
    counts, and the share of traces each segment dominated."""
    seg_values: Dict[str, List[float]] = {}
    retries: Dict[str, int] = {}
    dominated: Dict[str, int] = {}
    e2e: List[float] = []
    for a in analyses:
        if not a.get("spans"):
            continue
        e2e.append(a["e2e_ms"])
        for seg, ms in a["segments_ms"].items():
            seg_values.setdefault(seg, []).append(ms)
        for seg, n in (a.get("retries") or {}).items():
            retries[seg] = retries.get(seg, 0) + n
        if a.get("dominant"):
            dominated[a["dominant"]] = dominated.get(a["dominant"], 0) + 1
    segments = {
        seg: {"p50_ms": round(_percentile(vals, 50), 3),
              "p99_ms": round(_percentile(vals, 99), 3),
              "mean_ms": round(sum(vals) / len(vals), 3),
              "max_ms": round(max(vals), 3),
              "n": len(vals)}
        for seg, vals in seg_values.items()}
    report = {
        "traces_analyzed": len(e2e),
        "e2e_ms": {"p50": round(_percentile(e2e, 50), 3),
                   "p99": round(_percentile(e2e, 99), 3),
                   "mean": round(sum(e2e) / len(e2e), 3) if e2e else 0.0,
                   "n": len(e2e)},
        "segments": segments,
        "retries": retries,
        "dominated_by": dominated,
    }
    if coverage is not None:
        report["coverage"] = coverage
    return report


def aggregate_report(recorder) -> Dict:
    """The ``/debug/criticalpath`` payload: analyze every complete
    trace currently retained by ``recorder`` (a
    :class:`~tpu_dra_driver.pkg.tracing.FlightRecorder`) and aggregate,
    with eviction-aware coverage so the numbers are never silently
    partial."""
    by_trace: Dict[str, List[Dict]] = {}
    spans = recorder.all_spans()
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    analyses = [analyze(trace_spans) for trace_spans in by_trace.values()]
    evicted = getattr(recorder, "evicted", 0)
    return aggregate(analyses, coverage={
        "spans_retained": len(spans),
        "spans_evicted": evicted,
        "traces_evicted": getattr(recorder, "evicted_traces", 0),
        "complete": evicted == 0,
    })
