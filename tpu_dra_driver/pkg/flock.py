"""Polling file-lock wrapper with timeout, released on fd close (crash-safe).

Reference analog: pkg/flock/flock.go:31-135 — a polling
``flock(LOCK_EX|LOCK_NB)`` wrapper used for the node-global
prepare/unprepare lock (``pu.lock``) and the checkpoint lock (``cp.lock``).
Because the lock is tied to the open file descriptor, a crashed process
releases it automatically when the kernel closes its fds.
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import os
import time
from dataclasses import dataclass


class FlockTimeoutError(TimeoutError):
    """Raised when the lock cannot be acquired within the timeout."""


@dataclass
class FlockOptions:
    timeout: float = 10.0       # seconds; <=0 means a single non-blocking try
    poll_interval: float = 0.01  # seconds between LOCK_NB attempts


class Flock:
    """An exclusive advisory lock on a file path.

    The fd is kept open for the lifetime of the lock so that process death
    releases it. Re-entrant acquisition from the same Flock object is an
    error (mirrors the reference's usage discipline).
    """

    def __init__(self, path: str, options: FlockOptions | None = None):
        self._path = path
        self._options = options or FlockOptions()
        self._fd: int | None = None

    @property
    def path(self) -> str:
        return self._path

    @property
    def held(self) -> bool:
        return self._fd is not None

    def acquire(self, timeout: float | None = None) -> None:
        if self._fd is not None:
            raise RuntimeError(f"flock {self._path}: already held by this object")
        t = self._options.timeout if timeout is None else timeout
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
        deadline = time.monotonic() + max(t, 0.0)
        try:
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    self._fd = fd
                    return
                except OSError as e:
                    if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN, errno.EACCES):
                        raise
                if time.monotonic() >= deadline:
                    raise FlockTimeoutError(
                        f"timed out after {t:.1f}s acquiring lock {self._path}"
                    )
                time.sleep(self._options.poll_interval)
        except BaseException:
            if self._fd is None:
                os.close(fd)
            raise

    def release(self) -> None:
        if self._fd is None:
            return
        fd, self._fd = self._fd, None
        with contextlib.suppress(OSError):
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)

    def __enter__(self) -> "Flock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def locked(path: str, timeout: float = 10.0) -> Flock:
    """Convenience: ``with locked('/run/.../pu.lock'):``"""
    return Flock(path, FlockOptions(timeout=timeout))
