"""Rate-limited keyed workqueues with latest-wins semantics.

Reference analog: pkg/workqueue/workqueue.go + jitterlimiter.go — a thin
wrapper over client-go's rate-limited workqueue providing:

- ``enqueue`` / ``enqueue_with_key`` with *latest-wins* semantics per key
  (workqueue.go:152-190): if an item with the same key is re-enqueued before
  its previous incarnation ran, only the newest callback/payload runs.
- Three limiter flavors (workqueue.go:49-63):
  * controller default (item-exponential 5ms→1000s composed with a
    10/s + burst-100 bucket),
  * prepare/unprepare (item-exponential 250ms→3s composed with a global
    5/s bucket),
  * compute-domain daemon (exponential 5ms→6s with ±25% jitter,
    jitterlimiter.go:15-63).

This is a from-scratch Python implementation (threads + condition variable +
time heap), not a translation; only the observable semantics match.
"""

from __future__ import annotations

import heapq
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


# ---------------------------------------------------------------------------
# Rate limiters
# ---------------------------------------------------------------------------

class RateLimiter:
    """Computes the delay before an item (by key) may run again."""

    def when(self, key: str) -> float:
        raise NotImplementedError

    def forget(self, key: str) -> None:
        pass

    def num_requeues(self, key: str) -> int:
        return 0


class ItemExponentialFailureRateLimiter(RateLimiter):
    """base * 2^failures, capped at max_delay; per-key failure counts."""

    def __init__(self, base_delay: float, max_delay: float):
        self._base = base_delay
        self._max = max_delay
        self._failures: dict[str, int] = {}
        self._mu = threading.Lock()

    def when(self, key: str) -> float:
        with self._mu:
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
        delay = self._base * (2 ** n)
        return min(delay, self._max)

    def forget(self, key: str) -> None:
        with self._mu:
            self._failures.pop(key, None)

    def num_requeues(self, key: str) -> int:
        with self._mu:
            return self._failures.get(key, 0)


class BucketRateLimiter(RateLimiter):
    """Token bucket: qps tokens/second with the given burst size.

    ``when`` returns how long the caller must wait for its reserved token.
    """

    def __init__(self, qps: float, burst: int):
        self._qps = qps
        self._burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._mu = threading.Lock()

    def when(self, key: str) -> float:
        with self._mu:
            now = time.monotonic()
            self._tokens = min(self._burst, self._tokens + (now - self._last) * self._qps)
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self._qps


class JitteredExponentialRateLimiter(RateLimiter):
    """Exponential backoff with multiplicative jitter.

    Reference analog: pkg/workqueue/jitterlimiter.go:15-63 — delay =
    base * 2^failures (capped), then multiplied by a uniform factor in
    [1-jitter, 1+jitter].
    """

    def __init__(self, base_delay: float, max_delay: float, jitter: float = 0.25,
                 rng: Optional[random.Random] = None):
        self._inner = ItemExponentialFailureRateLimiter(base_delay, max_delay)
        self._jitter = jitter
        self._rng = rng or random.Random()

    def when(self, key: str) -> float:
        delay = self._inner.when(key)
        factor = 1.0 + self._rng.uniform(-self._jitter, self._jitter)
        return max(0.0, delay * factor)

    def forget(self, key: str) -> None:
        self._inner.forget(key)

    def num_requeues(self, key: str) -> int:
        return self._inner.num_requeues(key)


class MaxOfRateLimiter(RateLimiter):
    """Composite limiter: the worst (largest) delay of its children wins."""

    def __init__(self, *limiters: RateLimiter):
        self._limiters = limiters

    def when(self, key: str) -> float:
        return max((lim.when(key) for lim in self._limiters), default=0.0)

    def forget(self, key: str) -> None:
        for lim in self._limiters:
            lim.forget(key)

    def num_requeues(self, key: str) -> int:
        return max((lim.num_requeues(key) for lim in self._limiters), default=0)


def default_controller_rate_limiter() -> RateLimiter:
    """client-go's DefaultControllerRateLimiter shape."""
    return MaxOfRateLimiter(
        ItemExponentialFailureRateLimiter(0.005, 1000.0),
        BucketRateLimiter(qps=10.0, burst=100),
    )


def prep_unprep_rate_limiter() -> RateLimiter:
    """Reference workqueue.go:49-59: item-exponential 250ms→3s + global 5/s."""
    return MaxOfRateLimiter(
        ItemExponentialFailureRateLimiter(0.25, 3.0),
        BucketRateLimiter(qps=5.0, burst=10),
    )


def cd_daemon_rate_limiter(rng: Optional[random.Random] = None) -> RateLimiter:
    """Reference workqueue.go:61-63: exponential 5ms→6s with ±25% jitter."""
    return JitteredExponentialRateLimiter(0.005, 6.0, 0.25, rng=rng)


# ---------------------------------------------------------------------------
# Workqueue
# ---------------------------------------------------------------------------

@dataclass(order=True)
class _HeapEntry:
    ready_at: float
    seq: int
    key: str = field(compare=False)
    gen: int = field(compare=False)


class WorkQueue:
    """Keyed, rate-limited, latest-wins work queue.

    ``enqueue(fn)`` uses an auto key (one-shot); ``enqueue_with_key(key, fn)``
    coalesces: only the most recently enqueued fn for a key runs. A running
    fn that raises is retried with the limiter's backoff; returning normally
    forgets the key's failure history.

    Run with ``run(stop_event)`` on the caller's thread, or ``start()`` for a
    daemon thread.
    """

    def __init__(self, rate_limiter: Optional[RateLimiter] = None, name: str = "workqueue",
                 metrics: Optional[Any] = None):
        self._limiter = rate_limiter or default_controller_rate_limiter()
        self._name = name
        self._metrics = metrics  # pkg.metrics.QueueMetrics or None
        self._mu = threading.Condition()
        self._heap: list[_HeapEntry] = []
        # key -> (gen, fn, enqueued_at)
        self._items: dict[str, tuple[int, Callable[[], Any], float]] = {}
        self._seq = 0
        self._autokey = 0
        self._shutdown = False
        self._thread: Optional[threading.Thread] = None
        self._inflight = 0

    # -- producers ----------------------------------------------------------

    def enqueue(self, fn: Callable[[], Any]) -> str:
        with self._mu:
            self._autokey += 1
            key = f"__auto__{self._autokey}"
        self.enqueue_with_key(key, fn)
        return key

    def enqueue_with_key(self, key: str, fn: Callable[[], Any], delay: float = 0.0) -> None:
        with self._mu:
            if self._shutdown:
                return
            # Generation must be globally monotonic: a per-key counter would
            # reset once the key is popped, letting a stale delayed heap
            # entry from an earlier incarnation match a re-enqueued item's
            # generation and fire it before its scheduled delay.
            self._seq += 1
            self._items[key] = (self._seq, fn, time.monotonic())
            heapq.heappush(
                self._heap, _HeapEntry(time.monotonic() + delay, self._seq, key, self._seq)
            )
            if self._metrics:
                self._metrics.adds.inc()
                self._metrics.depth.set(len(self._items))
            self._mu.notify_all()

    def forget(self, key: str) -> None:
        self._limiter.forget(key)

    def num_requeues(self, key: str) -> int:
        return self._limiter.num_requeues(key)

    # -- consumer -----------------------------------------------------------

    def _pop_ready(self, stop: threading.Event) -> Optional[tuple[str, int, Callable[[], Any]]]:
        with self._mu:
            while True:
                if self._shutdown or stop.is_set():
                    return None
                now = time.monotonic()
                while self._heap:
                    entry = self._heap[0]
                    cur = self._items.get(entry.key)
                    if cur is None or cur[0] != entry.gen:
                        heapq.heappop(self._heap)  # stale: superseded or done
                        continue
                    break
                if self._heap and self._heap[0].ready_at <= now:
                    entry = heapq.heappop(self._heap)
                    gen, fn, enqueued_at = self._items.pop(entry.key)
                    self._inflight += 1
                    if self._metrics:
                        self._metrics.depth.set(len(self._items))
                        self._metrics.queue_duration.observe(now - enqueued_at)
                    return entry.key, gen, fn
                timeout = (self._heap[0].ready_at - now) if self._heap else 0.2
                self._mu.wait(timeout=min(timeout, 0.2))

    def run(self, stop: threading.Event) -> None:
        while True:
            got = self._pop_ready(stop)
            if got is None:
                return
            key, gen, fn = got
            started = time.monotonic()
            try:
                fn()
            except Exception:
                if self._metrics:
                    self._metrics.work_duration.observe(time.monotonic() - started)
                    self._metrics.retries.inc()
                delay = self._limiter.when(key)
                with self._mu:
                    self._inflight -= 1
                    # Re-enqueue only if nothing newer arrived meanwhile.
                    if key not in self._items and not self._shutdown:
                        self._items[key] = (gen, fn, time.monotonic())
                        self._seq += 1
                        heapq.heappush(
                            self._heap,
                            _HeapEntry(time.monotonic() + delay, self._seq, key, gen),
                        )
                        if self._metrics:
                            self._metrics.depth.set(len(self._items))
                    self._mu.notify_all()
            else:
                if self._metrics:
                    self._metrics.work_duration.observe(time.monotonic() - started)
                self._limiter.forget(key)
                with self._mu:
                    self._inflight -= 1
                    self._mu.notify_all()

    def start(self, workers: int = 1) -> threading.Event:
        stop = threading.Event()
        for i in range(workers):
            t = threading.Thread(
                target=self.run, args=(stop,), name=f"{self._name}-{i}", daemon=True
            )
            t.start()
        return stop

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Test helper: block until no queued or in-flight items remain."""
        deadline = time.monotonic() + timeout
        with self._mu:
            while self._items or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._mu.wait(timeout=min(remaining, 0.05))
            return True

    def shutdown(self) -> None:
        with self._mu:
            self._shutdown = True
            self._items.clear()
            self._heap.clear()
            self._mu.notify_all()
