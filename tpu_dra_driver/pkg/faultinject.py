"""Deterministic, process-global fault injection for chaos drills.

Reference analog: the reference driver's crash-safety (checkpoint
write-ahead, cleanup.go, IMEX daemon restarts) is proven on real clusters
by killing pods at unlucky moments; this module makes those moments
*schedulable* so the in-repo drill suite (tests/test_chaos_drills.py,
testing/harness.py) can kill a component at every dangerous instant and
assert the system converges.

Design constraints, in priority order:

1. **Zero overhead when disabled.** Production code calls
   :func:`fire` on hot paths (every checkpoint write, every REST
   request). Disabled, ``fire`` is one module-global bool check and a
   return — no dict lookup, no lock, no allocation. Guarded by a
   call-count assertion in the drill suite.
2. **Deterministic.** Schedules are counter-based (fail the Nth call,
   fail the first K then recover, every Nth) or seeded-random — a drill
   that passes once passes always.
3. **Scriptable.** Rules are armed in-process (:func:`arm`) or from the
   environment (:func:`arm_from_env`, ``TPU_DRA_FAULTS``) so subprocess
   components in the sim-cluster e2e suite can be scripted without code
   changes.
4. **Observable.** Every firing increments
   ``dra_fault_injections_total{point,mode}``.

Fault-point naming: ``<component>.<site>`` (catalog in docs/chaos.md).
A point is *declared* where it fires via :func:`register` so the drill
matrix can enumerate the catalog; firing an undeclared name still works
(it is auto-registered) to keep the seam friction-free.

Actions:

- ``fail``   — raise an exception (factory/instance supplied by the rule;
  default :class:`FaultInjected`),
- ``crash``  — raise :class:`CrashInjected`, which drills treat as the
  component dying at that instant (no cleanup runs past the raise
  site); with ``hard=True`` the process actually ``os._exit(137)``s —
  the SIGKILL analog for subprocess drills,
- ``latency`` — sleep ``seconds`` (timeout/slow-path exercise),
- ``corrupt`` — pass the payload through the rule's ``mutate`` callable
  and return the mutated value (torn bytes, flipped fields),
- ``pause``  — block on the rule's :class:`PauseGate` until a test
  resumes it (bounded by ``seconds``, default 120): the GC-pause /
  SIGSTOP analog. Unlike ``latency`` the stall is *externally
  controlled* — the split-brain drills park a lease holder's renew loop
  and commit path here, let a survivor adopt the slot, then resume the
  stale holder mid-write.

A rule may also carry a ``match`` predicate over the fire-site payload
(e.g. a claim UID or a lease identity) so one process-global point can
target a single victim — the pause drills stall exactly one replica's
elector while its rival keeps renewing through the same code path.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)

ENV_VAR = "TPU_DRA_FAULTS"


class FaultInjected(Exception):
    """Default exception raised by a ``fail`` rule."""


class CrashInjected(FaultInjected):
    """The component 'dies' here: drills catch this at the component
    boundary, discard the component without cleanup, and restart it."""


class PauseGate:
    """Externally-controlled stall for ``pause`` rules (the GC-pause /
    SIGSTOP analog). Starts RUNNING: an armed pause rule costs nothing
    until a drill calls :meth:`pause`; every thread that then hits the
    fire site blocks until :meth:`resume` (or the rule's ``seconds``
    ceiling, so a leaked gate can never wedge a suite)."""

    def __init__(self):
        self._running = threading.Event()
        self._running.set()

    def pause(self) -> None:
        self._running.clear()

    def resume(self) -> None:
        self._running.set()

    @property
    def paused(self) -> bool:
        return not self._running.is_set()

    def wait(self, timeout: float) -> bool:
        return self._running.wait(timeout)


@dataclass
class Rule:
    """One armed behavior on a fault point.

    Scheduling (counter-based, 1-indexed on the point's call count at
    the moment the rule was armed): exactly one of

    - ``nth``   — fire only on call #nth,
    - ``first`` — fire on calls 1..first, then recover,
    - ``every`` — fire on every ``every``-th call,
    - ``probability`` — fire with probability p from a seeded RNG,
    - none of the above — fire on every call (``always``).

    ``max_fires`` bounds total firings (0 = unbounded).
    """

    mode: str = "fail"                  # fail | crash | latency | corrupt | pause
    error: Optional[Callable[[], BaseException]] = None
    seconds: float = 0.0                # latency mode; pause-mode ceiling
    mutate: Optional[Callable] = None   # corrupt mode
    hard: bool = False                  # crash mode: os._exit(137)
    gate: Optional[PauseGate] = None    # pause mode
    #: payload predicate: when set, the rule only considers calls whose
    #: fire-site payload it accepts (checked before schedule counting,
    #: so nth/first/every count only the victim's calls)
    match: Optional[Callable] = None
    nth: int = 0
    first: int = 0
    every: int = 0
    probability: float = 0.0
    seed: int = 0
    max_fires: int = 0
    # filled in by the registry
    calls: int = 0
    fires: int = 0
    _rng: Optional[random.Random] = field(default=None, repr=False)

    def should_fire(self) -> bool:
        self.calls += 1
        if self.max_fires and self.fires >= self.max_fires:
            return False
        if self.nth:
            return self.calls == self.nth
        if self.first:
            return self.calls <= self.first
        if self.every:
            return self.calls % self.every == 0
        if self.probability:
            if self._rng is None:
                self._rng = random.Random(self.seed)
            return self._rng.random() < self.probability
        return True


@dataclass
class _Point:
    name: str
    description: str = ""
    calls: int = 0          # counted only while the subsystem is armed
    fired: int = 0
    rules: List[Rule] = field(default_factory=list)


#: Module-global fast-path flag: False means fire() returns immediately.
_ARMED = False
_LOCK = threading.Lock()
_POINTS: Dict[str, _Point] = {}


def register(name: str, description: str = "") -> None:
    """Declare a fault point (idempotent). Firing auto-registers too;
    explicit registration exists so the catalog is enumerable before
    any call reaches the site."""
    with _LOCK:
        p = _POINTS.get(name)
        if p is None:
            _POINTS[name] = _Point(name, description)
        elif description and not p.description:
            p.description = description


def catalog() -> Dict[str, str]:
    """name -> description for every declared point."""
    with _LOCK:
        return {n: p.description for n, p in sorted(_POINTS.items())}


def arm(name: str, rule: Rule) -> Rule:
    """Attach ``rule`` to ``name`` (registering it if needed) and enable
    the subsystem. Returns the rule so tests can read .calls/.fires."""
    global _ARMED
    with _LOCK:
        p = _POINTS.setdefault(name, _Point(name))
        rule.calls = 0
        rule.fires = 0
        p.rules.append(rule)
        _ARMED = True
    log.warning("fault point %s ARMED: %s", name, rule)
    return rule


def disarm(name: str) -> None:
    global _ARMED
    with _LOCK:
        p = _POINTS.get(name)
        if p is not None:
            p.rules.clear()
        _ARMED = any(pt.rules for pt in _POINTS.values())


def remove_rule(name: str, rule: Rule) -> bool:
    """Surgically detach ONE rule from a point, leaving any other armed
    rules in place — how a bounded adversity window (the soak's fault
    'weather') ends without disturbing a drill that holds its own rule
    on the same point. Returns whether the rule was attached."""
    global _ARMED
    with _LOCK:
        p = _POINTS.get(name)
        removed = False
        if p is not None and rule in p.rules:
            p.rules.remove(rule)
            removed = True
        _ARMED = any(pt.rules for pt in _POINTS.values())
        return removed


def reset() -> None:
    """Disarm everything and zero counters (catalog entries survive)."""
    global _ARMED
    with _LOCK:
        for p in _POINTS.values():
            p.rules.clear()
            p.calls = 0
            p.fired = 0
        _ARMED = False


def armed() -> bool:
    return _ARMED


def armed_points() -> Dict[str, List[str]]:
    """point -> armed rule modes, for points with at least one rule —
    the ``/debug/vars`` arm-state surface: a doctor bundle must show
    whether a slow prepare was a drill."""
    with _LOCK:
        return {n: [r.mode for r in p.rules]
                for n, p in sorted(_POINTS.items()) if p.rules}


def point_stats(name: str) -> Dict[str, int]:
    with _LOCK:
        p = _POINTS.get(name)
        return ({"calls": p.calls, "fired": p.fired} if p is not None
                else {"calls": 0, "fired": 0})


def fire(name: str, payload=None):
    """The in-code fault point. Returns ``payload`` (possibly mutated by
    a corrupt rule). Raises whatever an armed fail/crash rule dictates.

    Disabled (the production state), this is ONE global bool check."""
    if not _ARMED:
        return payload
    return _fire_slow(name, payload)


def _fire_slow(name: str, payload):
    with _LOCK:
        p = _POINTS.setdefault(name, _Point(name))
        p.calls += 1
        due: List[Rule] = []
        for rule in p.rules:
            if rule.match is not None and not rule.match(payload):
                continue
            if rule.should_fire():
                rule.fires += 1
                p.fired += 1
                due.append(rule)
    for rule in due:
        _count_fired(name, rule.mode)
        _record_span_event(name, rule.mode)
        log.warning("fault point %s FIRED (%s, fire #%d)",
                    name, rule.mode, rule.fires)
        if rule.mode == "latency":
            time.sleep(rule.seconds)
        elif rule.mode == "pause":
            # block until the drill resumes the gate (bounded: a gate
            # nobody resumes must not hang the suite forever)
            gate = rule.gate if rule.gate is not None else PauseGate()
            gate.wait(rule.seconds or 120.0)
        elif rule.mode == "corrupt":
            if rule.mutate is not None:
                payload = rule.mutate(payload)
        elif rule.mode == "crash":
            if rule.hard:
                os._exit(137)  # the SIGKILL analog: no cleanup runs
            raise CrashInjected(f"injected crash at {name}")
        else:  # fail
            err = rule.error() if rule.error is not None else None
            raise err if err is not None else FaultInjected(
                f"injected failure at {name}")
    return payload


def _count_fired(name: str, mode: str) -> None:
    # imported lazily: metrics imports nothing from here, but keeping the
    # disabled path import-free keeps fire() allocation-free too
    from tpu_dra_driver.pkg import metrics as _metrics
    _metrics.FAULT_INJECTIONS.labels(name, mode).inc()


def _record_span_event(name: str, mode: str) -> None:
    """A firing inside a traced claim shows up as a span event, so the
    flight recorder answers 'was that slow prepare a drill?'. No-op (one
    bool check inside tracing) when tracing is off."""
    from tpu_dra_driver.pkg import tracing as _tracing
    _tracing.add_event("fault.injected", point=name, mode=mode)


# ---------------------------------------------------------------------------
# Environment scripting (subprocess drills in the sim-cluster e2e suite)
# ---------------------------------------------------------------------------
#
# TPU_DRA_FAULTS is a comma-separated list of clauses:
#
#     <point>=<mode>[:<arg>][@<when>]
#
# mode:  fail[:<message>] | crash[:hard] | latency:<seconds> | corrupt
# when:  nth:<n> | first:<k> | every:<n> | p:<prob>[:seed:<s>]
#        (omitted = always)
# (pause is deliberately NOT env-scriptable: it needs an in-process
# PauseGate a drill can resume — a subprocess nobody can resume would
# just be a crash with extra steps)
#
# Examples:
#     checkpoint.write.torn=crash:hard@nth:2
#     rest.request=fail@first:3,rest.watch.stream=fail@every:5
#     tpulib.enumerate_chips=latency:0.2@p:0.5:seed:7


def parse_rules(spec: str) -> Dict[str, Rule]:
    """Parse a TPU_DRA_FAULTS spec into {point: Rule}. Raises ValueError
    on malformed clauses (fail loud: a typo'd drill must not silently
    run fault-free)."""
    out: Dict[str, Rule] = {}
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        if "=" not in clause:
            raise ValueError(f"fault clause {clause!r}: missing '='")
        point, rest = clause.split("=", 1)
        when = ""
        if "@" in rest:
            rest, when = rest.split("@", 1)
        parts = rest.split(":")
        mode = parts[0]
        rule = Rule(mode=mode)
        if mode == "fail":
            if len(parts) > 1:
                msg = ":".join(parts[1:])
                rule.error = lambda m=msg: FaultInjected(m)
        elif mode == "crash":
            rule.hard = len(parts) > 1 and parts[1] == "hard"
        elif mode == "latency":
            if len(parts) < 2:
                raise ValueError(f"fault clause {clause!r}: "
                                 f"latency needs seconds")
            rule.seconds = float(parts[1])
        elif mode == "corrupt":
            # env-armed corruption uses the generic byte/str mangler
            rule.mutate = default_corruptor
        else:
            raise ValueError(f"fault clause {clause!r}: unknown mode "
                             f"{mode!r}")
        if when:
            w = when.split(":")
            if w[0] == "nth":
                rule.nth = int(w[1])
            elif w[0] == "first":
                rule.first = int(w[1])
            elif w[0] == "every":
                rule.every = int(w[1])
            elif w[0] == "p":
                rule.probability = float(w[1])
                if len(w) >= 4 and w[2] == "seed":
                    rule.seed = int(w[3])
            else:
                raise ValueError(f"fault clause {clause!r}: unknown "
                                 f"schedule {w[0]!r}")
        out[point.strip()] = rule
    return out


def arm_from_env(environ=None) -> int:
    """Arm rules from TPU_DRA_FAULTS; returns how many were armed.
    Called by every cmd/* entrypoint at startup so subprocess drills
    (tests/e2e/simcluster.py) can script faults into production
    binaries."""
    spec = (environ or os.environ).get(ENV_VAR, "")
    if not spec:
        return 0
    rules = parse_rules(spec)
    for point, rule in rules.items():
        arm(point, rule)
    return len(rules)


def torn_tail_corruptor(payload):
    """Drop the second half of a payload: models an append that tore
    mid-write (power cut with a partial final record on disk). Unlike
    :func:`default_corruptor` (which flips bytes — CRC damage anywhere),
    this produces exactly the torn-tail shape journal recovery must
    truncate-and-forget."""
    if isinstance(payload, bytes):
        return payload[: max(1, len(payload) // 2)]
    if isinstance(payload, str):
        return payload[: max(1, len(payload) // 2)]
    return payload


def default_corruptor(payload):
    """Generic payload mangler: good enough to break any checksum."""
    if isinstance(payload, bytes):
        return payload[:-1] + bytes([payload[-1] ^ 0xFF]) if payload else b"\xff"
    if isinstance(payload, str):
        return payload[:-1] + ("X" if not payload.endswith("X") else "Y") \
            if payload else "X"
    return payload
