"""Prometheus-style metrics registry + debug HTTP endpoint.

Reference analog: cmd/compute-domain-controller/main.go:372-419 — the
controller exposes client-go/workqueue/Go-runtime Prometheus metrics via
component-base legacyregistry plus full ``net/http/pprof`` when
``--http-endpoint`` is set. The kubelet plugins there rely on V(6) timing
log breadcrumbs instead; here the same breadcrumbs additionally feed
histograms so the ResourceClaim-to-ready metric (BASELINE.md north star)
is scrapeable, not just greppable.

From-scratch implementation of the Prometheus *text exposition format*
(counters, gauges, histograms with labels) — no client library dependency.
The pprof analog is ``/debug/threads`` (all-thread stack dump, the same
payload as the SIGUSR2 handler in :mod:`tpu_dra_driver.common.debug`).
"""

from __future__ import annotations

import json
import sys
import threading
import time
import traceback
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_INF = float("inf")

# client-go workqueue histogram buckets (seconds)
DEFAULT_TIME_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                        1.0, 2.5, 5.0, 10.0)


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _format_labels(names: Sequence[str], values: Sequence[str],
                   extra: Optional[List[Tuple[str, str]]] = None) -> str:
    pairs = [(n, v) for n, v in zip(names, values)]
    if extra:
        pairs.extend(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{n}="{_escape(str(v))}"' for n, v in pairs)
    return "{" + inner + "}"


def _format_value(v: float) -> str:
    if v == _INF:
        return "+Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _format_exemplar(ex: Optional[Tuple[Dict[str, str], float, float]]) -> str:
    """OpenMetrics exemplar suffix: `` # {trace_id="..."} value ts``.
    Empty string when the bucket has none — plain Prometheus scrapers
    that split on ``#`` still parse the sample unchanged."""
    if not ex:
        return ""
    labels, value, ts = ex
    inner = ",".join(f'{k}="{_escape(str(v))}"'
                     for k, v in sorted(labels.items()))
    return f" # {{{inner}}} {_format_value(value)} {round(ts, 3)}"


class _Metric:
    """Base: a named family with fixed label names and per-labelset children."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._mu = threading.Lock()

    def labels(self, *values: str):
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} label values, "
                f"got {len(values)}")
        key = tuple(str(v) for v in values)
        with self._mu:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _new_child(self):
        raise NotImplementedError

    def _iter_children(self) -> Iterable[Tuple[Tuple[str, ...], object]]:
        with self._mu:
            items = list(self._children.items())
        return items

    def render(self, exemplars: bool = False) -> List[str]:
        raise NotImplementedError


class _CounterChild:
    __slots__ = ("_value", "_mu")

    def __init__(self):
        self._value = 0.0
        self._mu = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._mu:
            self._value += amount

    @property
    def value(self) -> float:
        with self._mu:
            return self._value


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, label_names)
        if not label_names:
            self._children[()] = _CounterChild()

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        if self.label_names:
            raise ValueError(f"{self.name} has labels; use .labels(...)")
        self._children[()].inc(amount)

    @property
    def value(self) -> float:
        return self._children[()].value

    def values(self) -> Dict[Tuple[str, ...], float]:
        """Per-labelset cumulative values — the counter analog of
        :meth:`Histogram.snapshots` for the SLO engine's availability
        specs (reset handling is the caller's: a smaller value than the
        previous sample means restart, use the new value whole)."""
        return {key: child.value for key, child in self._iter_children()}

    def render(self, exemplars: bool = False) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, child in self._iter_children():
            lines.append(f"{self.name}{_format_labels(self.label_names, key)}"
                         f" {_format_value(child.value)}")
        return lines


class _GaugeChild:
    __slots__ = ("_value", "_mu")

    def __init__(self):
        self._value = 0.0
        self._mu = threading.Lock()

    def set(self, v: float) -> None:
        with self._mu:
            self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._mu:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._mu:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._mu:
            return self._value


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, label_names)
        if not label_names:
            self._children[()] = _GaugeChild()

    def _new_child(self):
        return _GaugeChild()

    def _self_child(self) -> _GaugeChild:
        if self.label_names:
            raise ValueError(f"{self.name} has labels; use .labels(...)")
        return self._children[()]

    def set(self, v: float) -> None:
        self._self_child().set(v)

    def inc(self, amount: float = 1.0) -> None:
        self._self_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._self_child().dec(amount)

    @property
    def value(self) -> float:
        return self._self_child().value

    def render(self, exemplars: bool = False) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, child in self._iter_children():
            lines.append(f"{self.name}{_format_labels(self.label_names, key)}"
                         f" {_format_value(child.value)}")
        return lines


class HistogramSnapshot:
    """A cheap point-in-time copy of one histogram child: per-bucket
    (non-cumulative) counts, sum, count.

    The SLO engine (pkg/slo.py) samples through :meth:`Histogram
    .snapshots` + :meth:`count_le` and keeps scalar cumulative
    (good, total) pairs in its window ring; :meth:`delta` is the
    bucket-level form of the same windowing for consumers that need
    full distributions between two points in time (benches, tooling).
    Both apply the SAME reset rule — a cumulative count that went
    BACKWARDS means the process restarted, and the current value IS
    the window's traffic, never a negative delta. :meth:`delta` is the
    canonical, unit-tested statement of that rule (tests/test_slo.py
    pins it across a simulated restart); ``SLOEngine._delta_since``
    mirrors it at scalar level."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...], counts: Sequence[int],
                 total: float, count: int):
        self.buckets = buckets
        self.counts = tuple(counts)
        self.sum = total
        self.count = count

    def count_le(self, threshold: float) -> int:
        """Observations in buckets whose upper bound is <= threshold —
        the 'good events' count for a latency SLO whose threshold sits
        on a bucket boundary (conservative for thresholds between
        bounds: only fully-below buckets count as good)."""
        good = 0
        for bound, c in zip(self.buckets, self.counts):
            if bound <= threshold:
                good += c
        return good

    def delta(self, prev: Optional["HistogramSnapshot"]
              ) -> "HistogramSnapshot":
        """Observations between ``prev`` and this snapshot. A counter
        reset (this.count < prev.count, i.e. the process restarted and
        the family started over) yields this snapshot whole — the
        post-restart traffic is the only truth available, never a
        negative delta."""
        if prev is None or self.count < prev.count \
                or prev.buckets != self.buckets:
            return self
        return HistogramSnapshot(
            self.buckets,
            [c - p for c, p in zip(self.counts, prev.counts)],
            self.sum - prev.sum, self.count - prev.count)


class _HistogramChild:
    __slots__ = ("_buckets", "_counts", "_sum", "_count", "_mu",
                 "_exemplars")

    def __init__(self, buckets: Sequence[float]):
        self._buckets = buckets
        self._counts = [0] * len(buckets)
        self._sum = 0.0
        self._count = 0
        self._mu = threading.Lock()
        # bucket index (len(_buckets) = +Inf) -> (labels, value, unix ts):
        # the LAST exemplar per bucket, OpenMetrics semantics — a latency
        # bucket links back to one concrete trace (pkg/tracing.py)
        self._exemplars: Dict[int, Tuple[Dict[str, str], float, float]] = {}

    def observe(self, v: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        with self._mu:
            self._sum += v
            self._count += 1
            idx = len(self._buckets)
            for i, bound in enumerate(self._buckets):
                if v <= bound:
                    self._counts[i] += 1
                    idx = i
                    break
            if exemplar:
                self._exemplars[idx] = (dict(exemplar), v, time.time())

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._mu:
            return list(self._counts), self._sum, self._count

    def exemplars(self) -> Dict[int, Tuple[Dict[str, str], float, float]]:
        with self._mu:
            return dict(self._exemplars)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text, label_names=(),
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(name, help_text, label_names)
        self._buckets = tuple(sorted(buckets))
        if not label_names:
            self._children[()] = _HistogramChild(self._buckets)

    def _new_child(self):
        return _HistogramChild(self._buckets)

    def observe(self, v: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        if self.label_names:
            raise ValueError(f"{self.name} has labels; use .labels(...)")
        self._children[()].observe(v, exemplar=exemplar)

    def _self_child(self) -> _HistogramChild:
        if self.label_names:
            raise ValueError(f"{self.name} has labels; use .labels(...)")
        return self._children[()]

    @property
    def sum(self) -> float:
        """Sum of observed values (unlabeled family) — lets benches and
        tests read deltas (e.g. candidates scanned) without scraping."""
        _, total, _ = self._self_child().snapshot()
        return total

    @property
    def count(self) -> int:
        """Number of observations (unlabeled family)."""
        _, _, count = self._self_child().snapshot()
        return count

    def snapshot(self) -> HistogramSnapshot:
        """Point-in-time snapshot of the unlabeled family."""
        counts, total, count = self._self_child().snapshot()
        return HistogramSnapshot(self._buckets, counts, total, count)

    def snapshots(self) -> Dict[Tuple[str, ...], HistogramSnapshot]:
        """Per-labelset snapshots (all children); the windowed-delta
        accessor the SLO engine consumes — see
        :class:`HistogramSnapshot`."""
        out: Dict[Tuple[str, ...], HistogramSnapshot] = {}
        for key, child in self._iter_children():
            counts, total, count = child.snapshot()
            out[key] = HistogramSnapshot(self._buckets, counts, total, count)
        return out

    def time(self):
        """Context manager observing the elapsed wall time in seconds."""
        return _Timer(self)

    def render(self, exemplars: bool = False) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, child in self._iter_children():
            counts, total, count = child.snapshot()
            # Exemplar suffixes are OpenMetrics syntax; the classic
            # text-format 0.0.4 parser reads tokens after the value as a
            # timestamp and would fail the WHOLE scrape. They are
            # therefore rendered only on request (the /metrics?exemplars=1
            # / Accept: openmetrics path), never on a default scrape.
            ex = child.exemplars() if exemplars else {}
            cumulative = 0
            for i, (bound, c) in enumerate(zip(self._buckets, counts)):
                cumulative += c
                le = _format_labels(self.label_names, key,
                                    extra=[("le", _format_value(bound))])
                lines.append(f"{self.name}_bucket{le} {cumulative}"
                             f"{_format_exemplar(ex.get(i))}")
            le = _format_labels(self.label_names, key, extra=[("le", "+Inf")])
            lines.append(f"{self.name}_bucket{le} {count}"
                         f"{_format_exemplar(ex.get(len(self._buckets)))}")
            plain = _format_labels(self.label_names, key)
            lines.append(f"{self.name}_sum{plain} {repr(total)}")
            lines.append(f"{self.name}_count{plain} {count}")
        return lines


class _Timer:
    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.monotonic() - self._t0)
        return False


class Registry:
    """A named collection of metric families, rendered in text format."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._mu = threading.Lock()

    def _register(self, metric: _Metric) -> _Metric:
        with self._mu:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric) or \
                        existing.label_names != metric.label_names:
                    raise ValueError(
                        f"metric {metric.name} re-registered with a "
                        f"different type or labels")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help_text: str,
                label_names: Sequence[str] = ()) -> Counter:
        return self._register(Counter(name, help_text, label_names))  # type: ignore[return-value]

    def gauge(self, name: str, help_text: str,
              label_names: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(name, help_text, label_names))  # type: ignore[return-value]

    def histogram(self, name: str, help_text: str,
                  label_names: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help_text, label_names, buckets))  # type: ignore[return-value]

    def get(self, name: str) -> Optional[_Metric]:
        """The registered family named ``name``, or None — the SLO
        engine resolves its spec's family references through this so a
        spec naming a family another component registers (e.g. the CD
        controller's per-instance registry) simply reports no traffic
        here instead of raising."""
        with self._mu:
            return self._metrics.get(name)

    def render(self, exemplars: bool = False) -> str:
        with self._mu:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out: List[str] = []
        for m in metrics:
            out.extend(m.render(exemplars=exemplars))
        return "\n".join(out) + "\n"


#: Process-wide default registry (the legacyregistry analog).
DEFAULT_REGISTRY = Registry()


# ---------------------------------------------------------------------------
# Claim-to-ready fast-path instrumentation. One shared set of families so
# the CEL compile cache (kube/cel.py), the checkpoint writer
# (plugin/checkpoint.py), and the group-commit prepare path
# (plugin/device_state.py, plugin/driver.py) all land in the same scrape —
# these counters are also the proof surface for the fast-path invariants
# (1 parse per expression per batch, 2 fsync-bearing checkpoint writes per
# prepared batch) asserted by tests/test_claim_fast_path.py.
# ---------------------------------------------------------------------------

CEL_COMPILE_CACHE_HITS = DEFAULT_REGISTRY.counter(
    "dra_cel_compile_cache_hits_total",
    "Selector compile-cache hits (expression reused without reparsing)")
CEL_COMPILE_CACHE_MISSES = DEFAULT_REGISTRY.counter(
    "dra_cel_compile_cache_misses_total",
    "Selector compile-cache misses (tokenize+parse actually ran)")
CEL_COMPILE_CACHE_EVICTIONS = DEFAULT_REGISTRY.counter(
    "dra_cel_compile_cache_evictions_total",
    "Compiled selectors evicted from the bounded LRU compile cache")
CHECKPOINT_WRITES = DEFAULT_REGISTRY.counter(
    "dra_checkpoint_writes_total",
    "Checkpoint file writes; each is one fsync-bearing atomic replace")
CHECKPOINT_FSYNCS = DEFAULT_REGISTRY.counter(
    "dra_checkpoint_fsyncs_total",
    "fsync(2) calls issued by checkpoint persistence, by target: "
    "file=checkpoint tmp file, dir=state directory after an atomic "
    "rename (rename durability), journal=append-only journal group "
    "commit",
    ("target",))
JOURNAL_APPEND_SECONDS = DEFAULT_REGISTRY.histogram(
    "dra_journal_append_seconds",
    "Wall time a committer waits for its journal records to become "
    "durable (enqueue to group-commit fsync completion)")
JOURNAL_GROUP_COMMIT_RECORDS = DEFAULT_REGISTRY.histogram(
    "dra_journal_group_commit_records",
    "Records coalesced into one journal fsync by the group-commit "
    "writer (batch size 1 = no cross-batch coalescing happened)")
JOURNAL_COMPACTION_SECONDS = DEFAULT_REGISTRY.histogram(
    "dra_journal_compaction_seconds",
    "Journal compaction duration (rewrite base atomically + truncate "
    "journal)")
JOURNAL_RECORDS = DEFAULT_REGISTRY.gauge(
    "dra_journal_records",
    "Records currently in the append-only checkpoint journal since "
    "the last compaction")
CDI_RENDER_CACHE_HITS = DEFAULT_REGISTRY.counter(
    "dra_cdi_render_cache_hits_total",
    "Claim CDI spec renders served from the content-keyed render "
    "cache (identical device shape re-used a prior render)")
CDI_RENDER_CACHE_MISSES = DEFAULT_REGISTRY.counter(
    "dra_cdi_render_cache_misses_total",
    "Claim CDI spec renders that actually built the spec object "
    "(first sighting of this device shape, or cache invalidated)")
CDI_SPECS_RESTORED = DEFAULT_REGISTRY.counter(
    "dra_cdi_specs_restored_total",
    "Claim CDI spec files rewritten at recovery from the checkpointed "
    "body (file missing or torn; journal mode defers the per-spec "
    "fsync to the group-committed journal record)")
PREPARE_BATCH_PHASE_SECONDS = DEFAULT_REGISTRY.histogram(
    "dra_prepare_batch_phase_seconds",
    "Group-commit prepare wall time by phase for one kubelet batch",
    ("phase",))
PREPARE_BATCH_CLAIMS = DEFAULT_REGISTRY.histogram(
    "dra_prepare_batch_claims",
    "Claims per NodePrepareResources group-commit batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
UNPREPARE_BATCH_CLAIMS = DEFAULT_REGISTRY.histogram(
    "dra_unprepare_batch_claims",
    "Claims per NodeUnprepareResources batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))


# ---------------------------------------------------------------------------
# Event-driven ComputeDomain rendezvous instrumentation. The controller
# registers its own families (dra_cd_rendezvous_seconds,
# dra_cd_status_sync_triggers_total, dra_cd_status_writes_total) on its
# per-instance registry so tests can observe them in isolation; only the
# informer-level families live here because informers have no registry
# handle and always land on the process default.
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Chaos-hardening instrumentation (PR 3): the fault-injection subsystem,
# checkpoint quarantine, the RestCluster circuit breaker / retry budget,
# and swallowed-error accounting for the reconcile/prepare paths (the
# test_lint.py except-Exception guard accepts an .inc() on this family as
# proof an error was observed, not silently dropped).
# ---------------------------------------------------------------------------

FAULT_INJECTIONS = DEFAULT_REGISTRY.counter(
    "dra_fault_injections_total",
    "Scheduled faults actually fired, by fault point and action mode",
    ("point", "mode"))
CHECKPOINT_QUARANTINED = DEFAULT_REGISTRY.counter(
    "dra_checkpoint_quarantined_total",
    "Corrupt checkpoint files quarantined to <path>.corrupt-<n> "
    "(the driver restarted from salvaged-or-empty state instead of "
    "crash-looping)")
CIRCUIT_BREAKER_STATE = DEFAULT_REGISTRY.gauge(
    "dra_circuit_breaker_state",
    "API-server circuit breaker state (0=closed, 1=half-open, 2=open)",
    ("name",))
CIRCUIT_BREAKER_TRANSITIONS = DEFAULT_REGISTRY.counter(
    "dra_circuit_breaker_transitions_total",
    "Circuit breaker state transitions",
    ("name", "to"))
RETRY_BUDGET_EXHAUSTED = DEFAULT_REGISTRY.counter(
    "dra_retry_budget_exhausted_total",
    "Retries skipped because the per-verb retry budget ran dry",
    ("verb",))
SWALLOWED_ERRORS = DEFAULT_REGISTRY.counter(
    "dra_swallowed_errors_total",
    "Exceptions absorbed (logged, not re-raised) on reconcile/prepare "
    "paths, by site",
    ("site",))


# ---------------------------------------------------------------------------
# Scale-out allocator instrumentation (indexed device catalog + incremental
# usage ledger + churn-free slice publishing). The candidates histogram is
# the proof surface for the index-probe claim: an indexed request observes
# the post-intersection candidate count, a fallback request the full fleet.
# ---------------------------------------------------------------------------

ALLOCATOR_CANDIDATES_SCANNED = DEFAULT_REGISTRY.histogram(
    "dra_allocator_candidates_scanned",
    "Candidate devices examined per device request (after index "
    "intersection when a probe plan applied, the full fleet otherwise)",
    buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384))
ALLOCATOR_INDEX_HITS = DEFAULT_REGISTRY.counter(
    "dra_allocator_index_hits_total",
    "Device requests whose candidate set came from catalog index "
    "intersection (outcome=index) vs the linear full-scan fallback "
    "(outcome=fallback)",
    ("outcome",))
ALLOCATION_SECONDS = DEFAULT_REGISTRY.histogram(
    "dra_allocation_seconds",
    "Wall time to allocate one ResourceClaim (snapshot scan + commit)")
ALLOCATION_RESULTS = DEFAULT_REGISTRY.counter(
    "dra_allocation_results_total",
    "Allocation attempts by outcome (ok / error / aborted — aborted "
    "= no availability verdict: claim vanished mid-allocation or "
    "stale-route redirect); the allocation error-rate SLO reads "
    "good=ok over total=ok+error",
    ("result",))
ALLOCATOR_COMMIT_CONFLICTS = DEFAULT_REGISTRY.counter(
    "dra_allocator_commit_conflicts_total",
    "Allocation status writes that hit a resourceVersion conflict and "
    "went through verify-on-commit")
ALLOCATOR_PARKED_CLAIMS = DEFAULT_REGISTRY.gauge(
    "dra_allocator_parked_claims",
    "ResourceClaims currently parked as unsatisfiable (no capacity or "
    "cross-shard ownership not converged), awaiting a fleet change; "
    "each parked claim also carries an AllocationParked Event")
ALLOCATION_COMMIT_PHASE_SECONDS = DEFAULT_REGISTRY.histogram(
    "dra_allocation_commit_phase_seconds",
    "Allocation commit-path wall time by sub-phase (verify_read / "
    "status_write / reserve_phase1 / await_grants / phase2_graduate / "
    "unwind) — the micro-attribution of the soak-dominant "
    "allocation.commit segment; each bucket carries the sub-span's "
    "trace exemplar on /metrics?exemplars=1",
    ("phase",),
    buckets=(1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))
CATALOG_SNAPSHOT_SECONDS = DEFAULT_REGISTRY.histogram(
    "dra_catalog_snapshot_seconds",
    "Wall time to obtain one consistent per-batch view, by source: "
    "catalog/ledger are the copy-on-write generation pins the allocator "
    "uses (near-O(1) by design), catalog-copy/ledger-copy the eager "
    "full-copy baseline arms kept for the bench comparison",
    ("source",),
    buckets=(1e-6, 1e-5, 1e-4, 1e-3, 0.01, 0.1, 1.0))
CATALOG_GENERATIONS = DEFAULT_REGISTRY.counter(
    "dra_catalog_generations_total",
    "Copy-on-write snapshot generations pinned (a pin of an "
    "already-pinned, unmutated generation does not count), by source "
    "(catalog = device indexes, ledger = usage)",
    ("source",))
CATALOG_BUCKET_CLONES = DEFAULT_REGISTRY.counter(
    "dra_catalog_bucket_clones_total",
    "Structures lazily cloned by catalog/ledger copy-on-write — the "
    "O(delta) work slice events and usage changes pay so pinned "
    "snapshots stay frozen — by family (toplevel = the per-generation "
    "shallow top-level dict copies, pool = device-store sub-maps, "
    "driver/node/attr = index buckets, ledger = the usage dict pair)",
    ("family",))
RESOURCESLICE_PUBLISHES = DEFAULT_REGISTRY.counter(
    "dra_resourceslice_publishes_total",
    "ResourceSlice API writes actually performed by republish()",
    ("op",))
RESOURCESLICE_PUBLISHES_SKIPPED = DEFAULT_REGISTRY.counter(
    "dra_resourceslice_publishes_skipped_total",
    "ResourceSlice writes skipped because the published content was "
    "already identical (churn-free republish)")


# ---------------------------------------------------------------------------
# Dynamic sub-slice repartitioning + shared-chip serving (plugin/
# repartition.py, plugin/sharing.py): the create/reclaim/rollback/adopt
# transitions of the crash-safe reshape state machine, the hardware cost
# of each reshape, and the live client-seat density on shared chips.
# ---------------------------------------------------------------------------

SUBSLICE_REPARTITIONS = DEFAULT_REGISTRY.counter(
    "dra_subslice_repartitions_total",
    "Dynamic sub-slice repartition state-machine transitions by "
    "operation (create = placement picked + partition created on "
    "prepare, reclaim = partition destroyed on unprepare, rollback = "
    "half-created placement torn down, adopt = committed claim's live "
    "partition adopted by recovery) and outcome",
    ("op", "outcome"))
SUBSLICE_RESHAPE_SECONDS = DEFAULT_REGISTRY.histogram(
    "dra_subslice_reshape_seconds",
    "Wall time of one chip reshape: the device-library partition "
    "create (op=create) or destroy (op=reclaim) a dynamic sub-slice "
    "claim paid, placement pick included",
    ("op",))
SHARED_CHIP_CLIENTS = DEFAULT_REGISTRY.gauge(
    "dra_shared_chip_clients",
    "Multi-process client seats currently attached across this node's "
    "shared chips (claim-per-request serving density)")


# ---------------------------------------------------------------------------
# Observability instrumentation (claim-lifecycle tracing + Kubernetes
# Events): the flight recorder counts every span it retains, and the
# Event recorder (kube/events.py) accounts for every emission outcome so
# dropped/deduplicated events stay visible even though they never reach
# the API server.
# ---------------------------------------------------------------------------

TRACE_SPANS_RECORDED = DEFAULT_REGISTRY.counter(
    "dra_trace_spans_recorded_total",
    "Finished spans retained by the in-process trace flight recorder "
    "(served at /debug/traces)")
TRACES_EVICTED = DEFAULT_REGISTRY.counter(
    "dra_traces_evicted_total",
    "Traces fully evicted from the bounded flight recorder (the last "
    "retained span pushed out to make room for newer ones); the "
    "critical-path aggregator reports this — plus span-level eviction "
    "— as coverage so latency attribution is never silently partial")
EVENTS_EMITTED = DEFAULT_REGISTRY.counter(
    "dra_events_emitted_total",
    "Kubernetes Events by emission outcome: created (new Event object), "
    "deduped (count bumped on an existing Event), dropped (rate "
    "limited), cleared (state-shaped Event deleted after its condition "
    "drained), error (API write failed, swallowed)",
    ("reason", "outcome"))


INFORMER_WATCH_LAG = DEFAULT_REGISTRY.histogram(
    "dra_informer_watch_lag_seconds",
    "Time a watch event waited between arrival and informer dispatch",
    ("resource",))


# ---------------------------------------------------------------------------
# Sharded control plane + multiplexed watch layer (consistent-hash
# allocator shards, kube/sharding.py; selector/asyncio watch mux,
# kube/aio.py). The shard gauges are the hand-off proof surface: a
# rebalance drill asserts ownership moved by watching
# dra_shard_rebalances_total tick while dra_shard_owned_pools converges
# on the survivor.
# ---------------------------------------------------------------------------

SHARD_OWNED_POOLS = DEFAULT_REGISTRY.gauge(
    "dra_shard_owned_pools",
    "Device pools currently routed to this process by the consistent-"
    "hash ring, by owned shard slot",
    ("slot",))
SHARD_REBALANCES = DEFAULT_REGISTRY.counter(
    "dra_shard_rebalances_total",
    "Shard-slot ownership transitions observed by this process "
    "(direction=acquired when a slot lease was won, lost when "
    "leadership lapsed or was handed off)",
    ("slot", "direction"))
LEADER_TRANSITIONS = DEFAULT_REGISTRY.counter(
    "dra_leader_transitions_total",
    "Lease-based leadership transitions, by lease name and direction "
    "(acquired/lost) — shard hand-offs and controller fail-overs both "
    "land here",
    ("lease", "direction"))
LEASE_EPOCH = DEFAULT_REGISTRY.gauge(
    "dra_lease_epoch",
    "Fencing epoch (Lease leaseTransitions) under which this process "
    "currently holds the named lease — every allocation-plane write is "
    "stamped with it, and a write behind the slot's current epoch is "
    "rejected (split-brain fencing, docs/chaos.md)",
    ("lease",))
FENCING_REJECTIONS = DEFAULT_REGISTRY.counter(
    "dra_fencing_rejections_total",
    "Allocation-plane writes rejected because their stamped lease epoch "
    "was behind the slot's current one (a paused/partitioned holder "
    "woke after a survivor adopted its slot), by rejection site — "
    "any nonzero value means fencing just prevented a split-brain "
    "double-allocation",
    ("site",))
WATCH_STREAMS_ACTIVE = DEFAULT_REGISTRY.gauge(
    "dra_watch_streams_active",
    "Watch subscriptions currently open, by transport: mux (fake/REST "
    "subs serviced by the shared watch mux), rest-thread (legacy "
    "thread-per-stream REST watches), rest-async (asyncio REST "
    "streams on the shared event loop)",
    ("transport",))
WATCH_MUX_LAG = DEFAULT_REGISTRY.histogram(
    "dra_watch_mux_lag_seconds",
    "Time from a watch event being pushed onto its subscription queue "
    "to the mux worker handing it to the informer (the event-to-handler "
    "window the thread-per-stream architecture paid a thread to bound)")
INFORMER_LISTER_HITS = DEFAULT_REGISTRY.counter(
    "dra_informer_lister_hits_total",
    "Lister reads served from informer stores (each replaces an API "
    "round-trip a poll-based sync would have paid)",
    ("resource",))


# ---------------------------------------------------------------------------
# In-process time-series ring (a small fixed-memory TSDB). A sampler
# periodically snapshots every registered family into bounded per-series
# rings — counters and gauges as raw values, histograms through recording
# rules (windowed p50/p99 over the delta since the previous tick, plus a
# per-second rate) — served at /debug/timeseries. Consumers: the doctor's
# LEAK_SUSPECTED / LEASE_FLAPPING trend fits (one fetch replaces the
# fleet-wide --resample sleep window), its sparkline bundle summaries, and
# the soak's leak sentinels.
# ---------------------------------------------------------------------------

TIMESERIES_SAMPLES = DEFAULT_REGISTRY.counter(
    "dra_timeseries_samples_total",
    "Sampling ticks the in-process time-series ring has taken over the "
    "registry (each tick appends one point per live series)")
TIMESERIES_SERIES_DROPPED = DEFAULT_REGISTRY.counter(
    "dra_timeseries_series_dropped_total",
    "New series the time-series ring refused because its fixed-memory "
    "series cap was reached (existing series keep sampling; the "
    "dropped family/labelset is absent from /debug/timeseries)")


def quantile_of_snapshot(snap: HistogramSnapshot,
                         q: float) -> Optional[float]:
    """Linear-interpolated quantile over a (windowed) histogram
    snapshot's buckets — the recording-rule math for the time-series
    ring and the bench arms. None when the window saw no traffic;
    observations above the last finite bucket clamp to that bound (the
    classic histogram_quantile behavior)."""
    if snap.count <= 0 or not snap.buckets:
        return None
    target = q * snap.count
    cum = 0.0
    prev_bound = 0.0
    for bound, c in zip(snap.buckets, snap.counts):
        if c and cum + c >= target:
            frac = (target - cum) / c
            return prev_bound + (bound - prev_bound) * frac
        cum += c
        prev_bound = bound
    return snap.buckets[-1]


def least_squares_slope(points: Sequence[Tuple[float, float]]
                        ) -> Optional[float]:
    """Per-second slope of a [(unix_ts, value), ...] series via ordinary
    least squares — the trend fit that upgrades two-point resample
    deltas. None for fewer than 2 points or a zero time span."""
    if len(points) < 2:
        return None
    n = float(len(points))
    mean_t = sum(p[0] for p in points) / n
    mean_v = sum(p[1] for p in points) / n
    num = sum((t - mean_t) * (v - mean_v) for t, v in points)
    den = sum((t - mean_t) ** 2 for t, _ in points)
    if den == 0:
        return None
    return num / den


class TimeSeriesRing:
    """Fixed-memory samples of a registry's families over time.

    Each tick appends (unix_ts, value) to a bounded per-series deque:

    - counters/gauges: one series per labelset, the raw value (a
      counter reset shows as a drop; readers apply the standard
      reset rule), plus a ``<name>:rate`` recording rule for counters
      (per-second delta vs the previous tick, reset -> resample);
    - histograms: ``<name>:count`` (cumulative observations) plus the
      ``<name>:p50`` / ``<name>:p99`` recording rules evaluated over
      the delta window since the previous tick (no point when the
      window saw no traffic).

    Memory is bounded two ways: ``capacity`` points per series and
    ``max_series`` series total (overflow counts under
    ``dra_timeseries_series_dropped_total`` — never silent)."""

    def __init__(self, registry: Optional[Registry] = None,
                 capacity: int = 360, interval: float = 5.0,
                 max_series: int = 4096):
        self._registry = registry or DEFAULT_REGISTRY
        self.capacity = int(capacity)
        self.interval = float(interval)
        self.max_series = int(max_series)
        self._series: Dict[str, deque] = {}
        self._prev_hist: Dict[str, HistogramSnapshot] = {}
        self._prev_counter: Dict[str, Tuple[float, float]] = {}
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling -------------------------------------------------------

    def _append(self, key: str, t: float, v: float) -> None:
        ring = self._series.get(key)
        if ring is None:
            if len(self._series) >= self.max_series:
                TIMESERIES_SERIES_DROPPED.inc()
                return
            ring = deque(maxlen=self.capacity)
            self._series[key] = ring
        ring.append((t, v))

    @staticmethod
    def _key(name: str, label_names: Sequence[str],
             label_values: Sequence[str], rule: str = "") -> str:
        base = name + (":" + rule if rule else "")
        return base + _format_labels(label_names, label_values)

    def tick(self, now: Optional[float] = None) -> None:
        """Take one sample of every registered family. Reader-side by
        design: the instrumented hot paths never see the ring — armed
        or not, ``observe()``/``inc()`` cost is unchanged."""
        t = time.time() if now is None else now
        with self._registry._mu:
            metrics_list = list(self._registry._metrics.values())
        with self._mu:
            for m in metrics_list:
                if isinstance(m, Counter):
                    for key, value in m.values().items():
                        skey = self._key(m.name, m.label_names, key)
                        self._append(skey, t, value)
                        prev = self._prev_counter.get(skey)
                        if prev is not None and t > prev[0] \
                                and value >= prev[1]:
                            self._append(
                                self._key(m.name, m.label_names, key,
                                          "rate"),
                                t, (value - prev[1]) / (t - prev[0]))
                        self._prev_counter[skey] = (t, value)
                elif isinstance(m, Gauge):
                    for key, child in m._iter_children():
                        self._append(
                            self._key(m.name, m.label_names, key),
                            t, child.value)
                elif isinstance(m, Histogram):
                    for key, snap in m.snapshots().items():
                        skey = self._key(m.name, m.label_names, key)
                        self._append(self._key(m.name, m.label_names,
                                               key, "count"),
                                     t, snap.count)
                        window = snap.delta(self._prev_hist.get(skey))
                        self._prev_hist[skey] = snap
                        if window.count > 0:
                            for rule, q in (("p50", 0.5), ("p99", 0.99)):
                                v = quantile_of_snapshot(window, q)
                                if v is not None:
                                    self._append(
                                        self._key(m.name, m.label_names,
                                                  key, rule), t, v)
            TIMESERIES_SAMPLES.inc()

    # -- background sampler ---------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.interval):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — sampler must survive
                    SWALLOWED_ERRORS.labels("timeseries.tick").inc()

        self._thread = threading.Thread(target=_run, name="timeseries",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- reading --------------------------------------------------------

    def series(self, key: str) -> List[Tuple[float, float]]:
        with self._mu:
            ring = self._series.get(key)
            return list(ring) if ring is not None else []

    def payload(self) -> Dict:
        """The /debug/timeseries body."""
        with self._mu:
            series = {k: [[round(t, 3), v] for t, v in ring]
                      for k, ring in sorted(self._series.items())}
        return {
            "enabled": True,
            "interval_s": self.interval,
            "capacity": self.capacity,
            "series": series,
        }


_TIMESERIES: Optional[TimeSeriesRing] = None


def timeseries_configure(interval: float = 5.0, capacity: int = 360,
                         registry: Optional[Registry] = None,
                         start: bool = True) -> TimeSeriesRing:
    """Arm the process-global time-series ring (flags.py wires this from
    --timeseries-interval; interval <= 0 leaves it disarmed). Replaces a
    prior ring (its sampler is stopped first)."""
    global _TIMESERIES
    if _TIMESERIES is not None:
        _TIMESERIES.stop()
    _TIMESERIES = TimeSeriesRing(registry=registry, capacity=capacity,
                                 interval=interval)
    if start:
        _TIMESERIES.start()
    return _TIMESERIES


def timeseries() -> Optional[TimeSeriesRing]:
    return _TIMESERIES


def timeseries_reset() -> None:
    """Disarm and drop the process-global ring (tests)."""
    global _TIMESERIES
    if _TIMESERIES is not None:
        _TIMESERIES.stop()
    _TIMESERIES = None


class QueueMetrics:
    """client-go workqueue metric set for one named queue.

    Families (matching upstream names): depth, adds_total, retries_total,
    queue_duration_seconds (enqueue→pop), work_duration_seconds.
    """

    def __init__(self, queue_name: str, registry: Optional[Registry] = None):
        reg = registry or DEFAULT_REGISTRY
        self.depth = reg.gauge(
            "workqueue_depth", "Current depth of the workqueue",
            ("name",)).labels(queue_name)
        self.adds = reg.counter(
            "workqueue_adds_total", "Total adds handled by the workqueue",
            ("name",)).labels(queue_name)
        self.retries = reg.counter(
            "workqueue_retries_total", "Total retries handled by the workqueue",
            ("name",)).labels(queue_name)
        self.queue_duration = reg.histogram(
            "workqueue_queue_duration_seconds",
            "How long an item stays queued before being processed",
            ("name",)).labels(queue_name)
        self.work_duration = reg.histogram(
            "workqueue_work_duration_seconds",
            "How long processing an item takes",
            ("name",)).labels(queue_name)


def dump_thread_stacks() -> str:
    """All-thread stack dump — the pprof goroutine-profile analog, same
    payload as the SIGUSR2 handler (internal/common/util.go:33-66)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in frames.items():
        header = f"--- thread {ident} ({names.get(ident, '?')}) ---"
        chunks.append(header + "\n" + "".join(traceback.format_stack(frame)))
    return "\n".join(chunks)


class DebugHTTPServer:
    """``--http-endpoint`` server: /metrics, /healthz, /readyz,
    /debug/threads (the net/http/pprof analog), the trace flight
    recorder at /debug/traces + /debug/traces/<trace-id>
    (pkg/tracing.py; empty JSON when tracing is disabled), the SLO
    engine at /debug/slo (pkg/slo.py), latency attribution at
    /debug/criticalpath[/<trace-id>] (pkg/criticalpath.py), the
    allocation decision ring at /debug/explain[/<claim-uid>]
    (kube/explain.py; ``enabled: false`` when disarmed), the
    time-series ring at /debug/timeseries (:func:`timeseries_configure`),
    and process vars at /debug/vars (``json_endpoints`` — build info,
    uptime, parsed flags, trace mode, fault-point arm state; the
    ``tpu-dra-doctor`` must-gather collects all of these).

    ``json_endpoints`` maps extra paths (e.g. ``/debug/vars``,
    ``/debug/allocator``) to zero-arg callables returning a
    JSON-serializable object; a callable that raises answers 500
    without taking the server down."""

    def __init__(self, address: Tuple[str, int],
                 registry: Optional[Registry] = None,
                 ready_check=None,
                 json_endpoints: Optional[Dict[str, object]] = None):
        self._registry = registry or DEFAULT_REGISTRY
        self._ready_check = ready_check or (lambda: True)
        self._json_endpoints = dict(json_endpoints or {})

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "text/plain; charset=utf-8") -> None:
                payload = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    # Exemplars only on the EXPLICIT ?exemplars=1 opt-in:
                    # the classic 0.0.4 text parser chokes on OpenMetrics
                    # exemplar suffixes, and scrapers pick their parser
                    # from our declared Content-Type — which stays 0.0.4.
                    # (Deliberately NOT keyed on the Accept header: stock
                    # Prometheus advertises openmetrics-text on every
                    # scrape, and honoring it without actually speaking
                    # OpenMetrics — # EOF framing, its content type —
                    # would fail every real scrape the moment one
                    # exemplar exists.)
                    want_exemplars = "exemplars=1" in query.split("&")
                    self._send(200,
                               outer._registry.render(
                                   exemplars=want_exemplars),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    self._send(200, "ok")
                elif path == "/readyz":
                    ok = False
                    try:
                        ok = bool(outer._ready_check())
                    except Exception:
                        ok = False
                    self._send(200 if ok else 503, "ok" if ok else "not ready")
                elif path == "/debug/threads":
                    self._send(200, dump_thread_stacks())
                elif path == "/debug/traces" or path == "/debug/traces/":
                    from tpu_dra_driver.pkg import tracing
                    self._send(200,
                               json.dumps(tracing.recorder().traces(),
                                          indent=1),
                               "application/json")
                elif path.startswith("/debug/traces/"):
                    from tpu_dra_driver.pkg import tracing
                    trace_id = path[len("/debug/traces/"):]
                    spans = tracing.recorder().trace(trace_id)
                    if spans:
                        self._send(200,
                                   json.dumps({"trace_id": trace_id,
                                               "spans": spans}, indent=1),
                                   "application/json")
                    else:
                        self._send(404, "trace not found")
                elif path == "/debug/slo" or path == "/debug/slo/":
                    # the process-global SLO engine's current evaluation
                    # ({} until flags.setup_observability armed one)
                    from tpu_dra_driver.pkg import slo
                    self._send(200, json.dumps(slo.report(), indent=1),
                               "application/json")
                elif path == "/debug/criticalpath" \
                        or path == "/debug/criticalpath/":
                    from tpu_dra_driver.pkg import criticalpath, tracing
                    self._send(200,
                               json.dumps(criticalpath.aggregate_report(
                                   tracing.recorder()), indent=1),
                               "application/json")
                elif path.startswith("/debug/criticalpath/"):
                    from tpu_dra_driver.pkg import criticalpath, tracing
                    trace_id = path[len("/debug/criticalpath/"):]
                    spans = tracing.recorder().trace(trace_id)
                    if spans:
                        self._send(200,
                                   json.dumps(criticalpath.analyze(spans),
                                              indent=1),
                                   "application/json")
                    else:
                        self._send(404, "trace not found")
                elif path == "/debug/timeseries" \
                        or path == "/debug/timeseries/":
                    ts = timeseries()
                    body = (ts.payload() if ts is not None
                            else {"enabled": False, "series": {}})
                    self._send(200, json.dumps(body, indent=1),
                               "application/json")
                elif path == "/debug/explain" or path == "/debug/explain/":
                    # lazy import (mirrors the tracing routes): pkg never
                    # imports kube at module load
                    from tpu_dra_driver.kube import explain
                    ring = explain.ring()
                    body = (ring.payload() if ring is not None
                            else {"enabled": False, "records": []})
                    self._send(200, json.dumps(body, indent=1),
                               "application/json")
                elif path.startswith("/debug/explain/"):
                    from tpu_dra_driver.kube import explain
                    uid = path[len("/debug/explain/"):]
                    rec = explain.lookup(uid)
                    if rec is not None:
                        self._send(200, json.dumps(rec, indent=1),
                                   "application/json")
                    else:
                        self._send(404, "explain record not found")
                elif path in outer._json_endpoints:
                    try:
                        body = json.dumps(outer._json_endpoints[path](),
                                          indent=1, default=str)
                    except Exception as e:  # noqa: BLE001 — debug surface
                        self._send(500, f"{type(e).__name__}: {e}")
                        return
                    self._send(200, body, "application/json")
                else:
                    self._send(404, "not found")

        self._server = ThreadingHTTPServer(address, Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="debug-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
