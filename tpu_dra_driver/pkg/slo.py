"""Declarative SLOs with multi-window burn-rate alerting, in-process.

PR 5 gave the driver raw telemetry (histograms, counters, traces,
Events); nothing *interpreted* it — an operator watching a fleet
scenario had to eyeball ``/metrics`` to decide whether claim-to-ready
was healthy. This module closes that gap the way Google-SRE-style
monitoring does (SRE workbook ch. 5, "alerting on SLOs"): each
:class:`SLOSpec` declares an objective over an existing metric family,
and the :class:`SLOEngine` evaluates it over sliding windows from
cheap snapshot accessors (:meth:`~tpu_dra_driver.pkg.metrics.Histogram
.snapshots` / :meth:`~tpu_dra_driver.pkg.metrics.Counter.values`; the
engine rings scalar cumulative (good, total) samples and applies the
counter-reset rule :class:`~tpu_dra_driver.pkg.metrics
.HistogramSnapshot.delta` pins at bucket level), computing the
**burn rate**:

    burn = (1 - SLI) / (1 - objective)

i.e. how many times faster than "exactly on budget" the error budget is
being spent. An SLO is *burning* when the burn rate exceeds a window's
threshold over BOTH its long and short range (the multi-window
multi-burn-rate pattern: the long window proves the problem is real,
the short window proves it is still happening — so alerts neither
flap on blips nor linger after recovery).

Surfaces:

- ``dra_slo_*`` gauge families on the default registry (scrapeable),
- ``/debug/slo`` JSON on every
  :class:`~tpu_dra_driver.pkg.metrics.DebugHTTPServer`,
- a deduped ``SLOBurnRate`` Kubernetes Event through the existing
  :class:`~tpu_dra_driver.kube.events.EventRecorder` while burning,
- the per-step SLI reports the fleet-scenario engine records
  (testing/scenarios.py) and the ``tpu-dra-doctor`` findings.

The engine only READS metric snapshots on its own thread — the observe
hot paths pay nothing for it (pinned by ``bench_slo_overhead``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from tpu_dra_driver.pkg import metrics as _metrics
from tpu_dra_driver.pkg.metrics import (
    Counter,
    DEFAULT_REGISTRY,
    Histogram,
    Registry,
)

LATENCY = "latency"
AVAILABILITY = "availability"


@dataclass(frozen=True)
class SLOSpec:
    """One service-level objective over one metric family.

    ``latency`` kind: good events are observations whose histogram
    bucket bound is <= ``threshold`` (thresholds should sit on bucket
    boundaries; between bounds the accounting is conservative). When
    the family is labeled, ``label_values`` restricts which children
    count as latency traffic at all — a result-labeled family must
    scope its latency SLO to successful requests, or an outage of
    FAST failures (1 ms validation errors) reads as perfect latency
    while zero claims actually become ready. Failures belong to the
    ``availability`` kind: children of a one-label family are
    classified by their label value — good when it is in
    ``good_label_values`` — and event counts come from counter values
    or histogram counts. ``label_values`` scopes availability traffic
    the same way: label values outside it (e.g. allocation attempts
    ``aborted`` because the claim vanished or the route went stale)
    are no attempts at all for the SLI — the 10k-node soak burned
    budget on exactly those false positives before this filter."""

    name: str
    family: str
    objective: float                      # e.g. 0.99 = "99% good"
    kind: str = LATENCY
    threshold: float = 0.0                # latency: good iff <= threshold
    #: latency kind, labeled families: only children whose first label
    #: value is in this set count (empty = all children)
    label_values: Tuple[str, ...] = ()
    good_label_values: Tuple[str, ...] = ()
    description: str = ""


@dataclass(frozen=True)
class BurnWindow:
    """One multi-window alert arm: burning when the burn rate is >=
    ``threshold`` over BOTH the long and the short range."""

    name: str
    long_s: float
    short_s: float
    threshold: float


#: The Google SRE workbook's recommended pairs: page-worthy fast burn
#: (2% of a 30d budget in 1h) and ticket-worthy slow burn, scaled to
#: the windows an in-process ring buffer can afford to remember.
DEFAULT_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow("fast", 3600.0, 300.0, 14.4),
    BurnWindow("slow", 21600.0, 1800.0, 6.0),
)

#: The driver's SLO catalog (docs/observability.md "SLOs & diagnostics").
#: Latency thresholds sit on DEFAULT_TIME_BUCKETS boundaries.
DEFAULT_SPECS: Tuple[SLOSpec, ...] = (
    SLOSpec("claim-prepare-latency", "dra_claim_prepare_duration_seconds",
            0.99, LATENCY, threshold=0.5, label_values=("ok",),
            description="99% of SUCCESSFUL NodePrepareResources claims "
                        "ready in <= 500ms (the claim-to-ready p99 "
                        "proxy on the kubelet side; failures are "
                        "prepare-availability's problem — counting "
                        "their fast error returns here would mask a "
                        "latency burn)"),
    SLOSpec("allocation-latency", "dra_allocation_seconds",
            0.99, LATENCY, threshold=0.25,
            description="99% of ResourceClaim allocations committed in "
                        "<= 250ms"),
    SLOSpec("cd-rendezvous-latency", "dra_cd_rendezvous_seconds",
            0.99, LATENCY, threshold=2.5,
            description="99% of ComputeDomain rendezvous (first daemon "
                        "join to Ready) in <= 2.5s"),
    SLOSpec("allocation-availability", "dra_allocation_results_total",
            0.999, AVAILABILITY, good_label_values=("ok",),
            label_values=("ok", "error"),
            description="99.9% of allocation attempts succeed "
                        "(result=aborted attempts — claim vanished "
                        "mid-allocation, stale-route redirects — carry "
                        "no availability verdict and are excluded)"),
    SLOSpec("prepare-availability", "dra_claim_prepare_duration_seconds",
            0.999, AVAILABILITY, good_label_values=("ok",),
            description="99.9% of claim prepares succeed (result label "
                        "of the prepare duration histogram)"),
)


# ---------------------------------------------------------------------------
# scrape surface (registered once; the lint gate keys on these sites)
# ---------------------------------------------------------------------------

SLO_SLI = DEFAULT_REGISTRY.gauge(
    "dra_slo_sli",
    "Measured service-level indicator (good/total) per SLO and "
    "evaluation window (window label: <burn-window>_long/_short); 1.0 "
    "on zero-traffic windows",
    ("slo", "window"))
SLO_BURN_RATE = DEFAULT_REGISTRY.gauge(
    "dra_slo_burn_rate",
    "Error-budget burn rate (bad fraction / allowed bad fraction) per "
    "SLO and window; 1.0 = spending exactly on budget",
    ("slo", "window"))
SLO_BUDGET_REMAINING = DEFAULT_REGISTRY.gauge(
    "dra_slo_error_budget_remaining",
    "Fraction of the error budget left over the longest configured "
    "window (1.0 = untouched, 0 = exhausted, negative = overspent)",
    ("slo",))
SLO_BURNING = DEFAULT_REGISTRY.gauge(
    "dra_slo_burning",
    "1 while the SLO's multi-window burn-rate alert condition holds "
    "(some window pair's long AND short burn rates >= its threshold); "
    "mirrored as a deduped SLOBurnRate Kubernetes Event",
    ("slo",))


def sample_spec(spec: SLOSpec,
                registries: Sequence[Registry]) -> Tuple[float, float]:
    """Cumulative ``(good, total)`` event counts for ``spec`` right now,
    resolved against the first registry that has the family. A family
    nobody registered (or of the wrong shape) reports zero traffic —
    a spec must never crash the component it observes."""
    fam = None
    for reg in registries:
        fam = reg.get(spec.family)
        if fam is not None:
            break
    if fam is None:
        return 0.0, 0.0
    if spec.kind == LATENCY and isinstance(fam, Histogram):
        good = total = 0
        for key, snap in fam.snapshots().items():
            if spec.label_values and (not key
                                      or key[0] not in spec.label_values):
                continue
            good += snap.count_le(spec.threshold)
            total += snap.count
        return float(good), float(total)
    if spec.kind == AVAILABILITY:
        if isinstance(fam, Counter):
            values = fam.values()
        elif isinstance(fam, Histogram):
            values = {k: float(s.count) for k, s in fam.snapshots().items()}
        else:
            return 0.0, 0.0
        good = total = 0.0
        for key, v in values.items():
            if spec.label_values and (not key
                                      or key[0] not in spec.label_values):
                continue  # outside the SLO's traffic (e.g. "aborted")
            total += v
            if key and key[0] in spec.good_label_values:
                good += v
        return good, total
    return 0.0, 0.0


def burn_rate(good_delta: float, total_delta: float,
              objective: float) -> Tuple[float, float]:
    """``(burn, sli)`` for one window's worth of traffic. Zero traffic
    is a PERFECT window (sli 1.0, burn 0): no evidence of badness must
    never page — the property tests pin this."""
    if total_delta <= 0:
        return 0.0, 1.0
    sli = min(1.0, max(0.0, good_delta / total_delta))
    budget = max(1e-9, 1.0 - objective)
    return (1.0 - sli) / budget, sli


class SLOEngine:
    """Samples spec families on a tick, keeps a bounded ring of
    timestamped cumulative counts, and evaluates burn rates over the
    configured windows. Everything is snapshot-delta based, so process
    restarts (counter resets) degrade to "window starts at restart"
    instead of negative traffic."""

    def __init__(self, registries: Optional[Sequence[Registry]] = None,
                 specs: Sequence[SLOSpec] = DEFAULT_SPECS,
                 windows: Sequence[BurnWindow] = DEFAULT_WINDOWS,
                 tick: float = 10.0,
                 component: str = "",
                 recorder=None,
                 involved: Optional[Dict[str, str]] = None,
                 now_fn=time.monotonic,
                 cumulative: bool = False):
        self._registries: List[Registry] = list(
            registries if registries is not None else [DEFAULT_REGISTRY])
        self.specs = tuple(specs)
        self.windows = tuple(windows)
        self.tick = tick
        self.component = component
        self._recorder = recorder
        self._involved = involved
        self._now = now_fn
        self._mu = threading.Lock()
        # serializes whole sample() passes: the family reads happen
        # outside _mu, and two interleaved passes can misread sampling
        # lag as a counter reset (pass B reads newer counts and lands
        # its stitch first; pass A's older total then looks like it
        # went backwards and the reset branch re-adds the WHOLE
        # cumulative history) — corrupting the budgets the soak's
        # verdict rides on
        self._sample_mu = threading.Lock()
        # spec name -> deque of (ts, good_cumulative, total_cumulative)
        self._samples: Dict[str, Deque[Tuple[float, float, float]]] = {
            s.name: deque() for s in self.specs}
        # Cumulative-budget mode (the endurance-soak judge): the sliding
        # windows above silently RE-OPEN the error budget whenever a
        # component restarts (counter reset => "window starts at
        # restart"), which is correct for paging but wrong for a
        # whole-run verdict. When armed, every sample() also stitches
        # (good, total) across resets into monotone accumulators, so a
        # plugin that restarts mid-burn still exhausts its budget.
        # (Blind spot, shared with any counter-reset heuristic: a reset
        # landing on EXACTLY the pre-restart counts is invisible for
        # one sample — a short tick makes that window negligible.)
        self._cumulative = cumulative
        # spec name -> [acc_good, acc_total, last_good, last_total]
        self._cum: Dict[str, List[float]] = {
            s.name: [0.0, 0.0, 0.0, 0.0] for s in self.specs}
        # the FIRST sample is the baseline: process-global families may
        # carry counts from before this engine existed (earlier bench
        # phases, other tests) — they are not this run's traffic
        self._cum_seeded: set = set()
        self._max_age = max((w.long_s for w in self.windows), default=0.0) \
            + 2 * max(tick, 1.0)
        self._last_report: Dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wiring ------------------------------------------------------------

    def add_registry(self, registry: Registry) -> None:
        """Components with per-instance registries (the CD controller's
        ``dra_cd_rendezvous_seconds``) make their families visible to
        the engine here."""
        with self._mu:
            if registry not in self._registries:
                self._registries.append(registry)

    def set_registries(self, registries: Sequence[Registry]) -> None:
        """Replace the registry set wholesale — how a restart is modeled
        in-process (the restarted component's families come back as
        fresh objects) and how tests swap in a post-restart registry.
        Cumulative accumulators survive: the next sample sees the reset
        and stitches."""
        with self._mu:
            self._registries = list(registries)

    def set_recorder(self, recorder, involved: Dict[str, str]) -> None:
        """Arm SLOBurnRate Event emission: ``recorder`` is the
        component's existing EventRecorder, ``involved`` the object the
        Event hangs off (the Node for kubelet plugins, the component
        identity for controllers)."""
        with self._mu:
            self._recorder = recorder
            self._involved = dict(involved)

    # -- sampling / evaluation ---------------------------------------------

    def sample(self) -> None:
        with self._sample_mu:
            self._sample_locked()

    def _sample_locked(self) -> None:
        now = self._now()
        with self._mu:
            registries = list(self._registries)
        for spec in self.specs:
            good, total = sample_spec(spec, registries)
            present = any(reg.get(spec.family) is not None
                          for reg in registries)
            with self._mu:
                ring = self._samples[spec.name]
                ring.append((now, good, total))
                # keep ONE sample older than the longest window so the
                # full-length delta stays computable; prune the rest
                while len(ring) > 2 and ring[1][0] <= now - self._max_age:
                    ring.popleft()
                if self._cumulative:
                    acc = self._cum[spec.name]
                    # the baseline must come from a PRESENT family: a
                    # spec whose family only materializes later (an
                    # add_registry() bringing counts from before this
                    # engine existed) seeds then, not at (0, 0) — else
                    # its pre-existing history would read as traffic.
                    # Limitation: family resolution MOVING between
                    # registries (first-match wins in sample_spec) is
                    # outside the restart model, which assumes the
                    # restarted component's families come back fresh.
                    if spec.name not in self._cum_seeded:
                        if present:
                            self._cum_seeded.add(spec.name)
                    # a cumulative count that went backwards is a counter
                    # reset (restart): the current cumulative is all new
                    # traffic. good and total reset together, so either
                    # going backwards means both restarted.
                    elif total < acc[3] or good < acc[2]:
                        acc[0] += good
                        acc[1] += total
                    else:
                        acc[0] += good - acc[2]
                        acc[1] += total - acc[3]
                    acc[2], acc[3] = good, total

    def _delta_since(self, spec: SLOSpec, now: float,
                     seconds: float) -> Tuple[float, float]:
        """(good, total) observed over the trailing ``seconds``. The
        base is the newest sample at/before the window start (or the
        oldest retained — a young process reports over its lifetime).
        A cumulative count that went BACKWARDS means the family reset
        (restart): the current cumulative IS the window's traffic."""
        with self._mu:
            ring = self._samples[spec.name]
            if not ring:
                return 0.0, 0.0
            _, cur_good, cur_total = ring[-1]
            base = ring[0]
            target = now - seconds
            for s in ring:
                if s[0] <= target:
                    base = s
                else:
                    break
        _, base_good, base_total = base
        if cur_total < base_total or cur_good < base_good:
            return cur_good, cur_total
        return cur_good - base_good, cur_total - base_total

    def evaluate(self) -> Dict:
        """One evaluation pass over the current ring: updates the
        ``dra_slo_*`` gauges, emits/refreshes SLOBurnRate Events, and
        returns (and caches, for /debug/slo) the report."""
        now = self._now()
        longest = max((w.long_s for w in self.windows), default=0.0)
        slos: Dict[str, Dict] = {}
        for spec in self.specs:
            spec_row: Dict = {
                "family": spec.family, "kind": spec.kind,
                "objective": spec.objective,
                "description": spec.description,
                "windows": {},
            }
            if spec.kind == LATENCY:
                spec_row["threshold_s"] = spec.threshold
            burning_pairs: List[str] = []
            for w in self.windows:
                arms = {}
                for arm, seconds in (("long", w.long_s),
                                     ("short", w.short_s)):
                    good, total = self._delta_since(spec, now, seconds)
                    burn, sli = burn_rate(good, total, spec.objective)
                    arms[arm] = {"sli": round(sli, 6),
                                 "burn_rate": round(burn, 3),
                                 "good": good, "total": total}
                    SLO_SLI.labels(spec.name, f"{w.name}_{arm}").set(sli)
                    SLO_BURN_RATE.labels(
                        spec.name, f"{w.name}_{arm}").set(burn)
                # >= threshold on BOTH arms, with real traffic on the
                # short arm: budget exhaustion exactly at the threshold
                # IS burning (the property tests pin the boundary)
                pair_burning = (
                    arms["long"]["burn_rate"] >= w.threshold
                    and arms["short"]["burn_rate"] >= w.threshold
                    and arms["short"]["total"] > 0)
                arms_row = dict(arms)
                arms_row["threshold"] = w.threshold
                arms_row["burning"] = pair_burning
                spec_row["windows"][w.name] = arms_row
                if pair_burning:
                    burning_pairs.append(w.name)
            good_l, total_l = self._delta_since(spec, now, longest)
            _, sli_l = burn_rate(good_l, total_l, spec.objective)
            budget = max(1e-9, 1.0 - spec.objective)
            remaining = 1.0 - (1.0 - sli_l) / budget
            burning = bool(burning_pairs)
            spec_row["burning"] = burning
            spec_row["burning_windows"] = burning_pairs
            spec_row["budget_remaining"] = round(remaining, 4)
            if self._cumulative:
                spec_row["cumulative"] = self.cumulative_budget(spec.name)
            SLO_BUDGET_REMAINING.labels(spec.name).set(remaining)
            SLO_BURNING.labels(spec.name).set(1.0 if burning else 0.0)
            self._emit_event(spec, spec_row)
            slos[spec.name] = spec_row
        report = {
            "component": self.component,
            "generated_unix": round(time.time(), 3),
            "tick_s": self.tick,
            "windows": [{"name": w.name, "long_s": w.long_s,
                         "short_s": w.short_s, "threshold": w.threshold}
                        for w in self.windows],
            "slos": slos,
        }
        with self._mu:
            self._last_report = report
        return report

    def evaluate_once(self) -> Dict:
        self.sample()
        return self.evaluate()

    def _emit_event(self, spec: SLOSpec, row: Dict) -> None:
        """While burning, (re-)emit the deduped Warning — the recorder
        aggregates repeats onto one Event object, so `kubectl describe`
        shows one SLOBurnRate with a climbing count, not a flood.

        The message must be DEDUPE-STABLE: the recorder keys its
        aggregation on the full (object, reason, message) tuple, so
        embedding the live burn rate would mint a fresh Event every
        tick as traffic drifts — flooding the object and draining its
        per-object token bucket. Live numbers live on /debug/slo and
        the dra_slo_* gauges; the Event names the condition and its
        static parameters only."""
        if not row["burning"] or self._recorder is None:
            return
        wname = row["burning_windows"][0]
        from tpu_dra_driver.kube.events import REASON_SLO_BURN_RATE
        involved = self._involved or {
            "kind": "Pod", "name": self.component or "tpu-dra-driver",
            "namespace": "tpu-dra-driver"}
        self._recorder.warning(
            involved, REASON_SLO_BURN_RATE,
            f"SLO {spec.name} burning: {wname}-window burn rate >= "
            f"{row['windows'][wname]['threshold']:g}x its error budget "
            f"of {1.0 - spec.objective:.4g} ({spec.family}; live rates "
            f"on /debug/slo and dra_slo_burn_rate)")

    def report(self) -> Dict:
        with self._mu:
            return dict(self._last_report)

    def burning(self) -> List[str]:
        """Names of SLOs currently burning (doctor/scenario surface)."""
        with self._mu:
            report = self._last_report
        return sorted(n for n, row in (report.get("slos") or {}).items()
                      if row.get("burning"))

    # -- cumulative budget (restart-stitched, whole-run accounting) --------

    def cumulative_budget(self, name: str) -> Dict:
        """The restart-stitched whole-run budget for one spec: total
        traffic, SLI, and the fraction of the error budget left
        (negative = overspent, i.e. EXHAUSTED). Requires
        ``cumulative=True``; zero-traffic runs report a full budget."""
        if not self._cumulative:
            raise RuntimeError("engine not in cumulative mode")
        spec = next(s for s in self.specs if s.name == name)
        with self._mu:
            good, total = self._cum[name][0], self._cum[name][1]
        _, sli = burn_rate(good, total, spec.objective)
        budget = max(1e-9, 1.0 - spec.objective)
        return {"good": good, "total": total,
                "sli": round(sli, 6),
                "objective": spec.objective,
                "budget_remaining": round(1.0 - (1.0 - sli) / budget, 4)}

    def cumulative_report(self) -> Dict[str, Dict]:
        """Per-spec :meth:`cumulative_budget` — the soak's pass/fail
        surface (exhaustion = any ``budget_remaining`` <= 0)."""
        return {s.name: self.cumulative_budget(s.name) for s in self.specs}

    def exhausted(self) -> List[str]:
        """Specs whose restart-stitched whole-run budget is spent."""
        return sorted(n for n, row in self.cumulative_report().items()
                      if row["total"] > 0 and row["budget_remaining"] <= 0)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self.sample()      # seed the ring so the first window has a base
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="slo-engine")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.tick):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — observer must never die
                _metrics.SWALLOWED_ERRORS.labels("slo.evaluate").inc()


# ---------------------------------------------------------------------------
# process-global engine (armed by flags.setup_observability)
# ---------------------------------------------------------------------------

_ENGINE: Optional[SLOEngine] = None


def configure(engine: Optional[SLOEngine]) -> Optional[SLOEngine]:
    """Install (and return) the process-global engine, stopping any
    predecessor; None disarms."""
    global _ENGINE
    if _ENGINE is not None:
        _ENGINE.stop()
    _ENGINE = engine
    return engine


def engine() -> Optional[SLOEngine]:
    return _ENGINE


def report() -> Dict:
    """The /debug/slo payload: the last evaluation, or {} when no
    engine is armed."""
    return _ENGINE.report() if _ENGINE is not None else {}


def attach_recorder(recorder, involved: Dict[str, str]) -> None:
    """Wire SLOBurnRate Events once a binary has its EventRecorder
    (recorders need API clients, which exist only after flag parsing)."""
    if _ENGINE is not None:
        _ENGINE.set_recorder(recorder, involved)


def add_registry(registry: Registry) -> None:
    if _ENGINE is not None:
        _ENGINE.add_registry(registry)


def reset() -> None:
    """Test helper: stop and drop the global engine."""
    configure(None)
