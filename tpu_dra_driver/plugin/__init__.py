"""plugin — the tpu-kubelet-plugin (reference analog: cmd/gpu-kubelet-plugin).

Per-node DRA plugin: enumerates TPU chips / dynamic sub-slices / vfio
devices, publishes ResourceSlices (incl. KEP-4815 partitionable devices
with shared counters), and serves Prepare/Unprepare with a crash-safe
checkpointed two-phase state machine and TPU-native CDI generation.
"""
