"""Device health monitoring → ResourceSlice republish without the device.

Reference analog: cmd/gpu-kubelet-plugin/device_health.go:30-351 — an NVML
event monitor (XidCriticalError / ECC) with a skip-list of benign XIDs;
an unhealthy device is removed from the published slices and never
re-healed automatically (an admin restarts the plugin after servicing).

TPU mapping: TpuLib health events. Benign-by-default kinds: thermal
slowdowns and maintenance preemptions (transient, runtime-handled). Fatal:
device errors and HBM ECC. ICI link errors are fatal for the *chip's*
schedulability here; the ComputeDomain daemon separately reacts to fabric
errors (CrashOnICIFabricErrors gate).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional, Set

from tpu_dra_driver.tpulib.interface import HealthEvent, HealthEventKind, TpuLib

log = logging.getLogger(__name__)

DEFAULT_BENIGN_KINDS = frozenset({
    HealthEventKind.THERMAL,
    HealthEventKind.PREEMPTED,
})


class DeviceHealthMonitor:
    def __init__(self, lib: TpuLib,
                 on_unhealthy: Callable[[str], None],
                 benign_kinds: Optional[Set[HealthEventKind]] = None):
        self._lib = lib
        self._on_unhealthy = on_unhealthy
        self._benign = DEFAULT_BENIGN_KINDS if benign_kinds is None else frozenset(benign_kinds)
        self._mu = threading.Lock()
        self._unhealthy: Set[str] = set()  # chip uuids
        self._unsub: Optional[Callable[[], None]] = None

    def start(self) -> None:
        self._unsub = self._lib.subscribe_health(self._handle)

    def stop(self) -> None:
        if self._unsub:
            self._unsub()
            self._unsub = None

    @property
    def unhealthy_uuids(self) -> Set[str]:
        with self._mu:
            return set(self._unhealthy)

    def _handle(self, event: HealthEvent) -> None:
        if event.kind in self._benign:
            log.info("ignoring benign health event %s on %s (code %d)",
                     event.kind.value, event.chip_uuid, event.code)
            return
        with self._mu:
            if event.chip_uuid in self._unhealthy:
                return
            self._unhealthy.add(event.chip_uuid)
        log.error("chip %s marked unhealthy: %s code=%d %s",
                  event.chip_uuid, event.kind.value, event.code, event.message)
        try:
            self._on_unhealthy(event.chip_uuid)
        except Exception:
            from tpu_dra_driver.pkg.metrics import SWALLOWED_ERRORS
            SWALLOWED_ERRORS.labels("health.on_unhealthy").inc()
            log.exception("unhealthy-device callback failed for %s", event.chip_uuid)
