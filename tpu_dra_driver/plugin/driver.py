"""The tpu-kubelet-plugin driver: startup, publish, Prepare/Unprepare.

Reference analog: cmd/gpu-kubelet-plugin/driver.go — startup order
(driver.go:66-173), node-global prepare/unprepare flock (``pu.lock``, 10 s
timeout, driver.go:341), prepare with timing breadcrumbs
(driver.go:334-386), health-event → republish-without-device
(driver.go:441-505), and the gRPC healthcheck self-probe (health.go).
Deliberate divergence: where the reference loops claims serially inside
NodePrepareResources, this driver group-commits the batch (one flock
acquisition + two checkpoint fsyncs per batch; see PARITY.md
"Claim-to-ready fast path").

The kubelet-facing transport (DRA plugin gRPC on ``dra.sock``) is provided
by :mod:`tpu_dra_driver.plugin.grpc_server`; this class is the
transport-independent core so tests and the e2e harness drive it directly
(the kubeletplugin.Helper seam).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from tpu_dra_driver.cdi.generator import CdiHandler, DEFAULT_CDI_ROOT
from tpu_dra_driver.kube.client import ClientSets
from tpu_dra_driver.kube.events import (
    EventRecorder,
    emit_claim_event,
    normalize_claim_refs,
)
from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg import featuregates as fg
from tpu_dra_driver.pkg import tracing
from tpu_dra_driver.pkg.flock import Flock, FlockOptions, FlockTimeoutError
from tpu_dra_driver.pkg.metrics import DEFAULT_REGISTRY, Registry, SWALLOWED_ERRORS
from tpu_dra_driver.plugin.checkpoint import PreparedDevice
from tpu_dra_driver.plugin.claims import ClaimInfo
from tpu_dra_driver.plugin.cleanup import CheckpointCleanupManager
from tpu_dra_driver.plugin.device_state import DeviceState
from tpu_dra_driver.plugin.health import DeviceHealthMonitor
from tpu_dra_driver.plugin.resourceslices import (
    LAYOUT_COMBINED,
    ResourceSlicePublisher,
)
from tpu_dra_driver.tpulib.interface import TpuLib

log = logging.getLogger(__name__)

PU_LOCK_TIMEOUT = 10.0  # reference driver.go:341


@dataclass
class PluginConfig:
    node_name: str
    state_dir: str                      # kubelet plugin dir
    cdi_root: str = DEFAULT_CDI_ROOT
    driver_root: str = "/"
    slice_layout: str = LAYOUT_COMBINED
    gates: fg.FeatureGates = field(default_factory=fg.FeatureGates)
    cleanup_interval: float = 600.0
    #: combined-layout slices holding more devices than this are split
    #: over multiple slices with stable name assignment (0 = unlimited)
    max_devices_per_slice: int = 0


@dataclass
class PrepareResult:
    devices: List[PreparedDevice] = field(default_factory=list)
    error: Optional[str] = None
    permanent: bool = False

    @property
    def cdi_device_ids(self) -> List[str]:
        out: List[str] = []
        for d in self.devices:
            out.extend(d.cdi_device_ids)
        return out


class TpuKubeletPlugin:
    def __init__(self, clients: ClientSets, lib: TpuLib, config: PluginConfig):
        self._clients = clients
        self._lib = lib
        self._config = config
        os.makedirs(config.state_dir, exist_ok=True)
        self._pu_lock_path = os.path.join(config.state_dir, "pu.lock")
        cdi = CdiHandler(cdi_root=config.cdi_root,
                         driver_root=config.driver_root,
                         driver_version=lib.driver_version())
        self.state = DeviceState(lib, config.gates, cdi, config.state_dir)
        self.publisher = ResourceSlicePublisher(
            clients.resource_slices, config.node_name,
            layout=config.slice_layout,
            max_devices_per_slice=config.max_devices_per_slice)
        # republish after vfio driver flips so sibling personalities
        # (chip vs vfio) are hidden/shown consistently (reference
        # driver.go:361-368,392-397)
        self.state.vfio.set_topology_change_callback(self._republish)
        self.health: Optional[DeviceHealthMonitor] = None
        if config.gates.enabled(fg.DEVICE_HEALTH_CHECK):
            self.health = DeviceHealthMonitor(lib, self._on_unhealthy)
        self.cleanup = CheckpointCleanupManager(
            self.state, clients.resource_claims,
            interval=config.cleanup_interval)
        # Prepared/Unprepared/Failed events on claims: `kubectl describe
        # resourceclaim` shows what this node actually did (the reference
        # only logs V(6) breadcrumbs)
        self._events = EventRecorder(clients.events,
                                     component="tpu-kubelet-plugin",
                                     host=config.node_name)
        self._started = False
        # Drain choreography: a cordoned node withdraws its ENTIRE pool
        # from the scheduler (republish with every device excluded) while
        # live claims keep being served — the DRA-level analog of
        # `kubectl cordon` for device capacity, flipped by the fleet
        # scenario engine / an operator before migrating claims away.
        self._cordoned = False
        # device-health stream state (kubelet's v1alpha1.DRAResourceHealth
        # service reads these; KEP-4680): a monotonically bumped version +
        # condvar so watchers wake exactly on changes
        self._health_cond = threading.Condition()
        self._health_version = 0
        self._health_stopped = False
        self._health_started_at = time.time()
        self._health_stamps: Dict[str, float] = {}   # chip uuid -> flip time
        # The ResourceClaim-to-ready north-star metric (BASELINE.md): the
        # scrapeable form of the reference's t_prep* log breadcrumbs.
        reg: Registry = DEFAULT_REGISTRY
        self._m_prepare = reg.histogram(
            "dra_claim_prepare_duration_seconds",
            "NodePrepareResources wall time per claim by result",
            ("result",))
        self._m_unprepare = reg.histogram(
            "dra_claim_unprepare_duration_seconds",
            "NodeUnprepareResources wall time per claim by result",
            ("result",))
        self._m_lock_wait = reg.histogram(
            "dra_prepare_lock_wait_seconds",
            "Node-global prepare/unprepare flock acquisition wait")

    # ------------------------------------------------------------------
    # lifecycle (reference driver.go:66-173)
    # ------------------------------------------------------------------

    @property
    def event_recorder(self) -> EventRecorder:
        """The plugin's Event sink — shared with the SLO engine so
        SLOBurnRate Warnings ride the same deduped async pipeline."""
        return self._events

    def start(self) -> None:
        if (self._config.gates.enabled(fg.DYNAMIC_SUBSLICE)
                or self._config.gates.enabled(fg.DYNAMIC_REPARTITION)):
            destroyed = self.state.destroy_unknown_subslices()
            if destroyed:
                log.warning("startup: destroyed %d unknown sub-slices: %s",
                            len(destroyed), destroyed)
        if self.health is not None:
            self.health.start()
        self.cleanup.start()
        self._republish()
        self._started = True
        log.info("tpu-kubelet-plugin started on node %s (%d allocatable devices)",
                 self._config.node_name, len(self.state.allocatable))

    def _pu_locked(self):
        """The NodePrepare/UnprepareResources serialization point. In
        journal mode batches must NOT serialize here — cross-batch group
        commit only coalesces fsyncs across batches that are actually in
        flight together; DeviceState's admission lock + the single
        journal-writer thread provide the consistency the flock used to."""
        if self.state.journal_mode:
            return contextlib.nullcontext()
        return Flock(self._pu_lock_path, FlockOptions(timeout=PU_LOCK_TIMEOUT))

    def shutdown(self) -> None:
        self.cleanup.stop()
        if self.health is not None:
            self.health.stop()
        # stop the journal group-commit writer + actuation pool (no-op in
        # rewrite mode): outstanding commits drain first, so an in-process
        # restart over the same state dir finds every acked record on disk
        self.state.close()
        # close the async Event worker promptly: an in-process restart
        # (drills, fleet servicing) must not strand one worker thread
        # per plugin generation (endurance-soak thread sentinel)
        self._events.stop(timeout=2.0)
        self._started = False
        # wake any device-health stream watchers parked in cond.wait so
        # SIGTERM exit isn't held hostage for up to the 30s poll period
        with self._health_cond:
            self._health_stopped = True
            self._health_cond.notify_all()

    def healthy(self) -> bool:
        """gRPC healthcheck analog (reference health.go:121-149 self-probes
        registration + a noop prepare): verify enumeration still answers and
        the checkpoint file is readable. Additionally NOT_SERVING while the
        API-server circuit breaker is open — kubelet must stop routing
        prepares into a backend that cannot resolve claims; serving resumes
        once a half-open probe succeeds."""
        cluster_healthy = getattr(self._clients.cluster, "healthy", None)
        if cluster_healthy is not None and not cluster_healthy():
            log.warning("healthcheck: API-server circuit breaker open")
            return False
        try:
            self._lib.enumerate_chips()
            self.state.get_checkpoint()
            return True
        except Exception:  # chaos-ok: health probe converts to NOT_SERVING
            log.exception("healthcheck failed")
            return False

    # ------------------------------------------------------------------
    # resource publishing
    # ------------------------------------------------------------------

    def _republish(self) -> None:
        self.state.refresh_allocatable()
        exclude = self._excluded_devices()
        # Counters must be emitted whenever a chip has multiple allocatable
        # personalities — dynamic sub-slices OR the chip/vfio pair — else
        # the scheduler could hand the same physical chip to two claims.
        gates = self._config.gates
        partitionable = (gates.enabled(fg.DYNAMIC_SUBSLICE)
                         or gates.enabled(fg.DYNAMIC_REPARTITION)
                         or gates.enabled(fg.SHARED_CHIP_SERVING)
                         or gates.enabled(fg.PASSTHROUGH_SUPPORT))
        self.publisher.republish(
            self.state.allocatable, exclude=exclude,
            partitionable=partitionable)

    def _excluded_devices(self) -> Set[str]:
        """Devices hidden from the scheduler: all personalities of unhealthy
        chips, plus consistency rules around live vfio bindings (a bound
        chip's runtime personality disappears; enumerate_allocatable already
        models that, so here only health). A cordoned node hides its whole
        pool."""
        if self._cordoned:
            return set(self.state.allocatable)
        exclude: Set[str] = set()
        unhealthy = self.health.unhealthy_uuids if self.health else set()
        for name, dev in self.state.allocatable.items():
            if dev.chip.uuid in unhealthy:
                exclude.add(name)
        gates = self._config.gates
        if (gates.enabled(fg.DYNAMIC_REPARTITION)
                or gates.enabled(fg.SHARED_CHIP_SERVING)):
            # remaining-creatable-capacity reflection: placements a live
            # partition overlaps, profile slots beyond free capacity,
            # seats on partitioned cores (repartition.py keeps the dirty
            # flag so every reshape triggers this republish; when these
            # gates are off the publisher's behavior is untouched)
            exclude |= self.state.repartition.exclusions(
                self.state.allocatable)
        return exclude

    @property
    def cordoned(self) -> bool:
        return self._cordoned

    def set_cordoned(self, cordoned: bool) -> None:
        """Flip drain state and republish: cordoned hides every device
        (new claims route to other nodes; the allocator's catalog sees
        an empty pool), uncordoned restores the full inventory. Already-
        prepared claims are untouched — draining them is the scenario
        choreography's job (unprepare + deallocate), not the publisher's."""
        if self._cordoned == cordoned:
            return
        self._cordoned = cordoned
        log.warning("node %s %s: republishing %s",
                    self._config.node_name,
                    "cordoned" if cordoned else "uncordoned",
                    "empty pool" if cordoned else "full inventory")
        self._republish()

    def _maybe_reshape_republish(self) -> None:
        """The advertise step of the repartition state machine: after a
        batch that reshaped a chip (partition created/reclaimed, seat
        attached/detached), republish so the slices reflect the REMAINING
        creatable capacity. Content-only rewrites — slice names never
        change — so the pool generation stays put (no churn). Best
        effort: a failed republish keeps the dirty flag, counted in
        dra_swallowed_errors_total, and the next reshape or periodic
        republish converges it."""
        gates = self._config.gates
        if not (gates.enabled(fg.DYNAMIC_REPARTITION)
                or gates.enabled(fg.SHARED_CHIP_SERVING)):
            return
        if not self.state.repartition.take_dirty():
            return
        try:
            fi.fire("repartition.advertise")
            self._republish()
        except Exception:  # chaos-ok: counted, dirty restored for retry
            SWALLOWED_ERRORS.labels("repartition.advertise").inc()
            self.state.repartition.mark_dirty()
            log.warning("reshape republish failed; capacity advertising "
                        "is stale until the next republish", exc_info=True)

    def _on_unhealthy(self, chip_uuid: str) -> None:
        log.warning("republishing slices without unhealthy chip %s", chip_uuid)
        self._republish()
        self._bump_health(chip_uuid)

    # ------------------------------------------------------------------
    # device-health stream (kubelet v1alpha1.DRAResourceHealth, KEP-4680)
    # ------------------------------------------------------------------

    def _bump_health(self, chip_uuid: str) -> None:
        with self._health_cond:
            self._health_version += 1
            self._health_stamps[chip_uuid] = time.time()
            self._health_cond.notify_all()

    def device_health(self) -> List[Dict]:
        """Current per-device health: every allocatable device name in
        this node's pool with healthy=False for devices whose underlying
        chip the monitor marked unhealthy. Includes hidden (excluded)
        personalities — kubelet needs the UNHEALTHY verdict precisely for
        devices no longer published. Timestamps are per-device flip
        times (KEP-4680 semantics), start time for never-flipped chips."""
        unhealthy = self.health.unhealthy_uuids if self.health else set()
        out = []
        for name, dev in sorted(self.state.allocatable.items()):
            out.append({
                "pool": self._config.node_name,
                "device": name,
                "healthy": dev.chip.uuid not in unhealthy,
                "stamp": self._health_stamps.get(dev.chip.uuid,
                                                 self._health_started_at),
            })
        return out

    def wait_health_change(self, seen_version: int,
                           timeout: float = 30.0) -> Optional[int]:
        """Block until the health version advances past ``seen_version``
        (or timeout); returns the current version, or None once the
        plugin is shutting down (watchers must end their streams).
        seen_version=-1 returns immediately (initial snapshot)."""
        with self._health_cond:
            if self._health_stopped:
                return None
            if seen_version < 0 or self._health_version > seen_version:
                return self._health_version
            self._health_cond.wait(timeout)
            if self._health_stopped:
                return None
            return self._health_version

    # ------------------------------------------------------------------
    # DRA entrypoints (reference driver.go:298-397)
    # ------------------------------------------------------------------

    def _claim_spans(self, claims: List[Dict]) -> Dict[str, object]:
        """One ``kubelet.prepare`` span per traced claim, parented on the
        traceparent annotation the allocator stamped — the cross-process
        pickup. Empty when tracing is disabled (the fast path)."""
        spans: Dict[str, object] = {}
        if not tracing.enabled():
            return spans
        for obj in claims:
            meta = obj.get("metadata") or {}
            uid = meta.get("uid", "")
            if not uid or uid in spans:
                continue
            span = tracing.start_span(
                "kubelet.prepare",
                parent=tracing.from_object(obj),
                attributes={
                    "claim": f"{meta.get('namespace', '')}/"
                             f"{meta.get('name', '')}",
                    "claim_uid": uid,
                    "node": self._config.node_name})
            if span.recording:
                spans[uid] = span
        return spans

    def prepare_resource_claims(self, claims: List[Dict]) -> Dict[str, PrepareResult]:
        """NodePrepareResources: the whole kubelet batch goes through the
        group-commit fast path — one pu-lock acquisition and two
        checkpoint fsyncs per BATCH (DeviceState.prepare_batch), not per
        claim, with per-claim error isolation. The per-claim duration
        histogram observes the amortized batch wall time (total / n):
        the cost kubelet actually pays per claim."""
        infos = ClaimInfo.from_objs(claims)
        if not infos:
            return {}
        spans = self._claim_spans(claims)
        # Batch-wide phase spans (write-ahead/commit fsyncs are shared
        # by the whole batch) nest under the first traced claim's span;
        # the claim attribute on per-claim child spans disambiguates.
        batch_span = next(iter(spans.values()), None)
        t0 = time.perf_counter()
        try:
            with self._pu_locked():
                t_lock = time.perf_counter() - t0
                self._m_lock_wait.observe(t_lock)
                with tracing.use_span(batch_span):
                    batch = self.state.prepare_batch(infos, spans=spans)
        except FlockTimeoutError as e:
            return self._prepare_batch_failed(
                infos, f"prepare lock: {e}", t0, spans)
        except Exception as e:  # chaos-ok: per-claim errors + error histogram
            # batch-wide failure (checkpoint read/corruption): no claim
            # got anywhere, so every claim reports it
            log.exception("prepare batch of %d claims failed", len(infos))
            return self._prepare_batch_failed(infos, str(e), t0, spans)
        elapsed = time.perf_counter() - t0
        log.debug("prepare batch of %d: pu-lock wait %.1fms, total %.1fms",
                  len(infos), t_lock * 1e3, elapsed * 1e3)
        per_claim = elapsed / len(infos)
        out: Dict[str, PrepareResult] = {}
        for info in infos:
            res = batch[info.uid]
            outcome = ("ok" if res.error is None
                       else "permanent_error" if res.permanent else "error")
            span = spans.get(info.uid)
            self._m_prepare.labels(outcome).observe(
                per_claim, exemplar=tracing.exemplar(span))
            if span is not None:
                span.set_attribute("result", outcome)
                span.set_attribute("cached", res.cached)
                span.end(status="ok" if res.error is None else "error")
            emit_claim_event(self._events, self._config.node_name,
                             self._claim_ref(info), "prepared",
                             error=res.error, permanent=res.permanent)
            out[info.uid] = PrepareResult(devices=res.devices,
                                          error=res.error,
                                          permanent=res.permanent)
        # the repartition advertise step runs OUTSIDE the pu-lock: the
        # batch already committed, this only refreshes published capacity
        self._maybe_reshape_republish()
        return out

    @staticmethod
    def _claim_ref(info: ClaimInfo) -> Dict[str, str]:
        return {"uid": info.uid, "name": info.name,
                "namespace": info.namespace}

    def _prepare_batch_failed(self, infos: List[ClaimInfo], error: str,
                              t0: float,
                              spans: Optional[Dict[str, object]] = None
                              ) -> Dict[str, PrepareResult]:
        per_claim = (time.perf_counter() - t0) / max(len(infos), 1)
        out: Dict[str, PrepareResult] = {}
        for info in infos:
            span = (spans or {}).get(info.uid)
            self._m_prepare.labels("error").observe(
                per_claim, exemplar=tracing.exemplar(span))
            if span is not None:
                span.set_attribute("error", error)
                span.end(status="error")
            emit_claim_event(self._events, self._config.node_name,
                             self._claim_ref(info), "prepared", error=error)
            out[info.uid] = PrepareResult(error=error, permanent=False)
        return out

    def unprepare_resource_claims(self, claim_refs: List) -> Dict[str, Optional[str]]:
        """NodeUnprepareResources, batched like the prepare side: one
        pu-lock acquisition + one checkpoint read/write for the whole
        batch (DeviceState.unprepare_batch), per-UID error strings
        preserved. ``claim_refs`` entries are bare uid strings or
        ``{"uid", "name", "namespace"}`` dicts (the gRPC layer passes the
        full kubelet refs so Events can name the claim)."""
        refs = normalize_claim_refs(claim_refs)
        claim_uids = list(refs)
        if not claim_uids:
            return {}
        t0 = time.perf_counter()
        try:
            with self._pu_locked():
                self._m_lock_wait.observe(time.perf_counter() - t0)
                batch = self.state.unprepare_batch(claim_uids)
        except Exception as e:  # chaos-ok: per-uid errors + error histogram
            log.exception("unprepare batch of %d claims failed",
                          len(claim_uids))
            per_claim = (time.perf_counter() - t0) / len(claim_uids)
            out: Dict[str, Optional[str]] = {}
            for uid in claim_uids:
                self._m_unprepare.labels("error").observe(per_claim)
                emit_claim_event(self._events, self._config.node_name,
                                 refs[uid], "unprepared", error=str(e))
                out[uid] = str(e)
            return out
        per_claim = (time.perf_counter() - t0) / len(claim_uids)
        out = {}
        for uid in claim_uids:
            exc = batch[uid]
            out[uid] = None if exc is None else str(exc)
            self._m_unprepare.labels(
                "ok" if exc is None else "error").observe(per_claim)
            emit_claim_event(self._events, self._config.node_name,
                             refs[uid], "unprepared", error=out[uid])
        self._maybe_reshape_republish()
        return out
