"""Periodic checkpoint cleanup of orphaned claims.

Reference analog: cmd/gpu-kubelet-plugin/cleanup.go:34-282
(CheckpointCleanupManager): every 10 minutes, scan checkpointed claims and
unprepare any whose ResourceClaim no longer exists in the API server — or
exists with a *different UID* (deleted and recreated under the same name).
This is the third prong of crash recovery: kubelet never calls Unprepare
for a claim it never successfully finished preparing.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from tpu_dra_driver.kube.client import ResourceClient
from tpu_dra_driver.kube.errors import NotFoundError
from tpu_dra_driver.plugin.device_state import DeviceState

log = logging.getLogger(__name__)

DEFAULT_INTERVAL = 600.0  # 10 min, matching the reference


class CheckpointCleanupManager:
    def __init__(self, state: DeviceState, claims_client: ResourceClient,
                 interval: float = DEFAULT_INTERVAL):
        self._state = state
        self._claims = claims_client
        self._interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="checkpoint-cleanup")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.sweep_once()
            except Exception:
                from tpu_dra_driver.pkg.metrics import SWALLOWED_ERRORS
                SWALLOWED_ERRORS.labels("cleanup.sweep").inc()
                log.exception("checkpoint cleanup sweep failed "
                              "(retried next interval)")

    def sweep_once(self) -> list[str]:
        """Unprepare checkpointed claims whose ResourceClaim is gone or has
        a changed UID. Returns the claim UIDs cleaned up."""
        cleaned = []
        cp = self._state.get_checkpoint()
        for uid, entry in list(cp.claims.items()):
            stale = False
            try:
                obj = self._claims.get(entry.claim_name, entry.namespace)
                if (obj.get("metadata") or {}).get("uid") != uid:
                    stale = True  # same name, different incarnation
            except NotFoundError:
                stale = True
            if stale:
                log.warning("cleanup: unpreparing stale claim %s/%s:%s",
                            entry.namespace, entry.claim_name, uid)
                self._state.unprepare(uid)
                cleaned.append(uid)
        return cleaned
