"""The dynamic repartitioning lifecycle: a crash-safe reshape state machine.

Reference analog: the DynamicMIG story in cmd/gpu-kubelet-plugin —
partitions are created on ``NodePrepareResources`` and reclaimed on
unprepare, and a plugin crash at ANY instant must not leak hardware
(mig.go's abstract-name recovery contract). This module owns every
transition of that lifecycle for TPU sub-slices:

- **place** — a PROFILE claim names a *creatable shape*, not a placement:
  the manager picks a free placement (live partitions, checkpoint intent
  and shared-chip client seats all honored), rolls back any half-created
  leftover from an earlier crashed attempt of ANY claim on that chip, and
  creates the megacore partition through the TpuLib seam;
- **reclaim** — unprepare destroys the partition by its abstract identity
  (parsed back from the canonical ``-ss-`` name alone — no live handle);
- **reconcile** — after a crash, live partitions (re-derived from
  canonical names via ``parse_canonical_name``) are reconciled against
  checkpoint intent: committed claims' partitions are ADOPTED, everything
  else (orphans, half-created placements) is torn down. Idempotent on
  re-crash: a reconcile that dies mid-sweep re-runs from the same truth;
- **advertise** — every transition marks the inventory dirty so the
  driver republishes the chip's REMAINING creatable capacity (overlapped
  placements and out-of-capacity profile slots hidden) without pool
  generation churn — content-only slice rewrites keep the generation.

Journaling rides the existing write-ahead/commit checkpoint: the placed
partition's canonical name is recorded in the claim's PrepareCompleted
entry (with the allocated profile-slot name in ``source_device``), so the
checkpoint IS the intent log and crash recovery needs exactly one parser.

Every transition is faultinject-instrumented (the ``repartition.*``
points below) and kill-drilled in tests/test_chaos_drills.py with the
PR-3 invariant contract: no leaked sub-slices, readable-or-quarantined
checkpoint, idempotent unprepare.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg.metrics import (
    SUBSLICE_REPARTITIONS,
    SUBSLICE_RESHAPE_SECONDS,
)
from tpu_dra_driver.plugin.allocatable import AllocatableDevice, DeviceType
from tpu_dra_driver.plugin.checkpoint import Checkpoint
from tpu_dra_driver.tpulib.interface import (
    ChipInfo,
    SubsliceAlreadyExistsError,
    SubsliceLiveTuple,
    SubsliceNotFoundError,
    TpuLib,
    TpuLibError,
)
from tpu_dra_driver.tpulib.partition import (
    SubsliceProfile,
    SubsliceSpec,
    SubsliceSpecTuple,
    parse_profile_id,
    seat_core,
)

log = logging.getLogger(__name__)

fi.register("repartition.place",
            "the placement pick for a dynamic profile claim (payload: the "
            "picked start core — corrupt models a broken picker, which "
            "the post-pick validation must catch; fail = pick error)")
fi.register("repartition.create",
            "between the claim's write-ahead and the partition create "
            "(crash = claim written-ahead, NO partition on the chip; "
            "restart rolls the attempt back and a retry re-places)")
fi.register("repartition.created",
            "between the partition create and the checkpoint commit "
            "(crash = LIVE partition the checkpoint only knows as "
            "PrepareStarted; restart must tear the orphan down)")
fi.register("repartition.reclaim",
            "the partition destroy on unprepare (fail = teardown error "
            "surfaced to kubelet, entry kept; the retry must be "
            "idempotent)")
fi.register("repartition.advertise",
            "the capacity-reflecting ResourceSlice republish after a "
            "reshape (fail = stale advertised capacity this round; the "
            "dirty flag survives so the next republish converges)")
fi.register("repartition.reconcile",
            "fired once per orphan live partition the recovery sweep "
            "tears down (crash mid-sweep = partial cleanup; re-running "
            "the sweep must be idempotent)")

MANIFEST_FILENAME = "partitions.json"


def checkpoint_owned_names(cp: Checkpoint) -> Set[str]:
    """Canonical device names any checkpoint entry claims. PrepareStarted
    entries carry no devices (the write-ahead records intent, not
    hardware), so this is effectively the committed set plus the current
    batch's in-flight completions."""
    return {d.canonical_name
            for e in cp.claims.values()
            for d in e.prepared_devices}


class RepartitionManager:
    """Owns the reshape state machine for one node's chips. All mutating
    entry points are called under DeviceState's lock + cp flock — this
    class adds no locking of its own beyond the dirty flag."""

    def __init__(self, lib: TpuLib, state_dir: str):
        self._lib = lib
        self._state_dir = state_dir
        self._dirty = False
        self._dirty_mu = threading.Lock()

    # ------------------------------------------------------------------
    # dirty flag (the advertise step's trigger)
    # ------------------------------------------------------------------

    def mark_dirty(self) -> None:
        with self._dirty_mu:
            self._dirty = True

    def take_dirty(self) -> bool:
        with self._dirty_mu:
            was = self._dirty
            self._dirty = False
            return was

    # ------------------------------------------------------------------
    # occupancy
    # ------------------------------------------------------------------

    def _live_on_chip(self, chip_index: int) -> List[SubsliceSpecTuple]:
        return [s.spec_tuple for s in self._lib.list_subslices()
                if s.spec_tuple.parent_index == chip_index]

    @staticmethod
    def _span(tup: SubsliceSpecTuple) -> Tuple[int, int]:
        cores, _ = parse_profile_id(tup.profile_id)
        return tup.placement_start, tup.placement_start + cores

    def _seat_cores(self, chip: ChipInfo) -> Set[int]:
        return {seat_core(k, chip.cores)
                for k in self._lib.list_multiprocess_seats(chip.uuid)}

    def free_placements(self, chip: ChipInfo, profile: SubsliceProfile,
                        occupied: Optional[List[Tuple[int, int]]] = None
                        ) -> List[int]:
        """Placement starts of ``profile`` on ``chip`` that overlap no
        live partition and cover no core carrying a client seat."""
        if occupied is None:
            occupied = [self._span(t)
                        for t in self._live_on_chip(chip.index)]
        seats = self._seat_cores(chip)
        out = []
        for start in profile.placements():
            lo, hi = start, start + profile.cores
            if any(lo < ohi and olo < hi for olo, ohi in occupied):
                continue
            if any(lo <= c < hi for c in seats):
                continue
            out.append(start)
        return out

    # ------------------------------------------------------------------
    # place: the create-on-prepare transition
    # ------------------------------------------------------------------

    def place(self, chip: ChipInfo, profile: SubsliceProfile,
              cp: Checkpoint) -> Tuple[SubsliceSpec, SubsliceLiveTuple]:
        """Pick a free placement for ``profile`` on ``chip`` and create
        the partition. Half-created leftovers on the chip (live
        partitions no checkpoint entry owns — an earlier crashed attempt)
        are rolled back first, so a retry after any failure starts from a
        clean chip."""
        t0 = time.perf_counter()
        owned = checkpoint_owned_names(cp)
        occupied: List[Tuple[int, int]] = []
        for tup in self._live_on_chip(chip.index):
            if tup.canonical_name() in owned:
                occupied.append(self._span(tup))
                continue
            # a live partition no claim owns: the half-created residue of
            # a crashed attempt — roll it back in place (the same cleanup
            # the startup reconcile performs, done lazily here so one
            # crashed claim cannot wedge the chip until the next restart)
            log.warning("place: rolling back orphan sub-slice %s",
                        tup.canonical_name())
            try:
                self._lib.destroy_subslice(tup)
                SUBSLICE_REPARTITIONS.labels("rollback", "ok").inc()
            except SubsliceNotFoundError:
                pass
            except TpuLibError:
                SUBSLICE_REPARTITIONS.labels("rollback", "error").inc()
                raise
        free = self.free_placements(chip, profile, occupied)
        if not free:
            # transient by design: capacity frees when a peer unprepares;
            # the scheduler's counter model admitted this slot, so the
            # usual cause is an in-flight reclaim racing the retry
            SUBSLICE_REPARTITIONS.labels("create", "error").inc()
            raise TpuLibError(
                f"no free {profile.id} placement on chip {chip.index} "
                f"(live: {[t.canonical_name() for t in self._live_on_chip(chip.index)]})")
        # highest free start: pre-cut -ss- placements allocate in
        # canonical (lowest-first) order, so dynamic picks grow from the
        # top and the two families meet in the middle instead of racing
        start = fi.fire("repartition.place", payload=free[-1])
        if start not in free:
            # a corrupt-mode fault (or a broken picker) handed back an
            # illegal placement: fail loudly, never create a misplaced
            # partition the checkpoint would then misname
            SUBSLICE_REPARTITIONS.labels("create", "error").inc()
            raise TpuLibError(
                f"picked placement {start!r} is not a free {profile.id} "
                f"placement on chip {chip.index} (free: {free})")
        spec = SubsliceSpec(chip.index, chip.uuid, profile, start)
        fi.fire("repartition.create")
        try:
            try:
                live = self._lib.create_subslice(spec)
            except SubsliceAlreadyExistsError:
                # raced residue the occupancy scan missed: recreate for a
                # clean slate (mirrors the pre-cut path's handling)
                self._lib.destroy_subslice(spec.tuple)
                live = self._lib.create_subslice(spec)
        except Exception:
            SUBSLICE_REPARTITIONS.labels("create", "error").inc()
            raise
        # manifest + dirty flag the instant the HARDWARE changed: a crash
        # between here and the checkpoint commit leaves a manifest that
        # truthfully lists the orphan (the doctor's SUBSLICE_ORPHANS
        # evidence), not a stale pre-reshape inventory
        self.mark_dirty()
        self.write_manifest()
        fi.fire("repartition.created")
        SUBSLICE_REPARTITIONS.labels("create", "ok").inc()
        SUBSLICE_RESHAPE_SECONDS.labels("create").observe(
            time.perf_counter() - t0)
        return spec, live

    # ------------------------------------------------------------------
    # reclaim: the destroy-on-unprepare transition
    # ------------------------------------------------------------------

    def reclaim(self, tup: SubsliceSpecTuple) -> bool:
        """Destroy by abstract identity. Returns False when the partition
        is already gone (idempotent retry / crashed teardown)."""
        t0 = time.perf_counter()
        fi.fire("repartition.reclaim")
        try:
            self._lib.destroy_subslice(tup)
        except SubsliceNotFoundError:
            return False
        except TpuLibError:
            SUBSLICE_REPARTITIONS.labels("reclaim", "error").inc()
            raise
        SUBSLICE_REPARTITIONS.labels("reclaim", "ok").inc()
        SUBSLICE_RESHAPE_SECONDS.labels("reclaim").observe(
            time.perf_counter() - t0)
        self.mark_dirty()
        self.write_manifest()
        return True

    # ------------------------------------------------------------------
    # reconcile: crash recovery (live partitions vs checkpoint intent)
    # ------------------------------------------------------------------

    def reconcile(self, cp: Checkpoint) -> List[str]:
        """The startup sweep (DestroyUnknownMIGDevices analog, state-
        machine edition): every live partition is re-derived from its
        canonical name and reconciled against checkpoint intent —
        committed claims' partitions adopted, orphans and half-created
        placements torn down. Idempotent on re-crash: the sweep reads
        hardware + checkpoint truth each run and never journals its own
        progress."""
        owned = checkpoint_owned_names(cp)
        destroyed: List[str] = []
        for live in self._lib.list_subslices():
            name = live.spec_tuple.canonical_name()
            if name in owned:
                SUBSLICE_REPARTITIONS.labels("adopt", "ok").inc()
                continue
            log.warning("reconcile: destroying unknown live sub-slice %s",
                        name)
            fi.fire("repartition.reconcile", payload=name)
            try:
                self._lib.destroy_subslice(live.spec_tuple)
                destroyed.append(name)
                SUBSLICE_REPARTITIONS.labels("rollback", "ok").inc()
            except SubsliceNotFoundError:
                pass
        if destroyed:
            self.mark_dirty()
        self.write_manifest()
        return destroyed

    # ------------------------------------------------------------------
    # advertise: remaining creatable capacity
    # ------------------------------------------------------------------

    def exclusions(self, allocatable: Dict[str, AllocatableDevice]
                   ) -> Set[str]:
        """Devices to hide from the scheduler so the published inventory
        reflects the chip's REMAINING creatable capacity after reshapes:

        - pre-cut ``-ss-`` placements overlapping a live partition,
        - profile slots beyond the count of still-free placements (slots
          are anonymous, so the highest indices hide first),
        - client seats whose core a live partition covers,
        - the whole-chip personality of any chip carrying partitions or
          seats (its counters already exclude it; hiding keeps the
          advertised inventory honest).
        """
        live_by_chip: Dict[int, List[Tuple[int, int]]] = {}
        for s in self._lib.list_subslices():
            live_by_chip.setdefault(s.spec_tuple.parent_index, []).append(
                self._span(s.spec_tuple))
        seat_cores_cache: Dict[int, Set[int]] = {}

        def seats_for(dev: AllocatableDevice) -> Set[int]:
            idx = dev.chip.index
            if idx not in seat_cores_cache:
                seat_cores_cache[idx] = self._seat_cores(dev.chip)
            return seat_cores_cache[idx]

        out: Set[str] = set()
        free_count: Dict[Tuple[int, str], int] = {}
        for name, dev in allocatable.items():
            occupied = live_by_chip.get(dev.chip.index, [])
            if dev.type == DeviceType.SUBSLICE:
                lo = dev.placement_start
                hi = lo + dev.profile.cores
                if any(lo < ohi and olo < hi for olo, ohi in occupied):
                    out.add(name)
            elif dev.type == DeviceType.PROFILE:
                key = (dev.chip.index, dev.profile.id)
                if key not in free_count:
                    free_count[key] = len(self.free_placements(
                        dev.chip, dev.profile, occupied))
                if dev.slot >= free_count[key]:
                    out.add(name)
            elif dev.type == DeviceType.SHARED:
                core = seat_core(dev.slot, dev.chip.cores)
                if any(olo <= core < ohi for olo, ohi in occupied):
                    out.add(name)
            elif dev.type == DeviceType.CHIP:
                if occupied or seats_for(dev):
                    out.add(name)
        return out

    # ------------------------------------------------------------------
    # the live-partition manifest (must-gather surface)
    # ------------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self._state_dir, MANIFEST_FILENAME)

    def write_manifest(self) -> None:
        """Best-effort diagnostic inventory of live partitions, dropped
        next to the checkpoint so tpu-dra-doctor's state-dir collection
        can cross-check live hardware against checkpoint intent (the
        SUBSLICE_ORPHANS finding) without reaching the device library.
        Diagnostic only — hardware + checkpoint stay the truth; a failed
        write must never fail the reshape that triggered it."""
        try:
            names = [s.spec_tuple.canonical_name()
                     for s in self._lib.list_subslices()]
            body = json.dumps({"updated_unix": round(time.time(), 3),
                               "partitions": names}, indent=1)
            tmp = f"{self.manifest_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(body + "\n")
            os.replace(tmp, self.manifest_path)
        except Exception:  # chaos-ok: diagnostic artifact, reshape already landed
            log.warning("could not write partition manifest", exc_info=True)
