"""The allocatable-device model: what this node can offer the scheduler.

Reference analog: cmd/gpu-kubelet-plugin/{allocatable.go:39-44,
deviceinfo.go:113-241, mig.go:98-131} — ``AllocatableDevice`` is a tagged
union (Gpu | MigDynamic | MigStatic | Vfio) keyed by canonical name. Here:

- ``CHIP``      — a whole TPU chip (``tpu-<index>``),
- ``SUBSLICE``  — an *abstract* dynamically-creatable sub-slice
  (``tpu-<index>-ss-<profile>-<start>``): advertised always, created only
  when a claim lands (the DynamicMIG model),
- ``VFIO``      — a chip offered for passthrough (``tpu-vfio-<index>``).

Each device renders to a DRA device entry with typed attributes, capacity,
and (for KEP-4815 layouts) counter consumption against its chip's
CounterSet.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Dict, Optional

from tpu_dra_driver.pkg import featuregates as fg
from tpu_dra_driver.tpulib.interface import ChipInfo, TpuLib
from tpu_dra_driver.tpulib.partition import (
    SubsliceProfile,
    canonical_chip_name,
    canonical_subslice_name,
    canonical_vfio_name,
    profiles_for,
)


class DeviceType(Enum):
    CHIP = "chip"
    SUBSLICE = "subslice"
    VFIO = "vfio"


@dataclass(frozen=True)
class AllocatableDevice:
    type: DeviceType
    chip: ChipInfo
    profile: Optional[SubsliceProfile] = None    # SUBSLICE only
    placement_start: int = 0                     # SUBSLICE only

    @property
    def canonical_name(self) -> str:
        if self.type == DeviceType.CHIP:
            return canonical_chip_name(self.chip.index)
        if self.type == DeviceType.SUBSLICE:
            assert self.profile is not None
            return canonical_subslice_name(self.chip.index, self.profile,
                                           self.placement_start)
        return canonical_vfio_name(self.chip.index)

    # -- DRA rendering ------------------------------------------------------

    def attributes(self) -> Dict[str, Dict]:
        """Typed DRA attributes (reference deviceinfo.go:159-241 publishes
        type/uuid/productName/architecture/pciBusID/pcieRoot/driverVersion;
        TPU adds torus coords + slice identity, which is what topology-aware
        scheduling selects on)."""
        c = self.chip
        attrs: Dict[str, Dict] = {
            "type": {"string": self.type.value},
            "uuid": {"string": c.uuid},
            "productName": {"string": c.product_name},
            "generation": {"string": c.generation.name},
            "pciBusID": {"string": c.pci_address},
            "pcieRoot": {"string": c.pci_root},
            "driverVersion": {"version": _semverish(c.driver_version)},
            "firmwareVersion": {"string": c.firmware_version},
            "sliceID": {"string": c.slice_id},
            "hostIndex": {"int": c.host_index},
            "iciBandwidthGbps": {"int": c.generation.ici_bandwidth_gbps},
        }
        for dim, val in zip(("coordX", "coordY", "coordZ"), c.coords):
            attrs[dim] = {"int": val}
        if self.type == DeviceType.SUBSLICE:
            assert self.profile is not None
            attrs["profile"] = {"string": self.profile.id}
            attrs["placementStart"] = {"int": self.placement_start}
        if self.type == DeviceType.VFIO:
            attrs["vfio"] = {"bool": True}
        return attrs

    def capacity(self) -> Dict[str, Dict]:
        if self.type == DeviceType.SUBSLICE:
            assert self.profile is not None
            cores = self.profile.cores
            hbm = self.profile.hbm_bytes
        else:
            cores = self.chip.cores
            hbm = self.chip.hbm_bytes
        return {
            "tensorcores": {"value": str(cores)},
            "hbm": {"value": str(hbm)},
        }

    def counter_consumption(self) -> Dict[str, Dict]:
        """KEP-4815: counters this device consumes from its chip's
        CounterSet. The full chip consumes *everything*, a sub-slice its
        cores + per-core memory slices — making chip and overlapping
        sub-slice allocations mutually exclusive for the scheduler
        (reference partitions.go:27-215)."""
        if self.type == DeviceType.SUBSLICE:
            assert self.profile is not None
            cores = self.profile.cores
            hbm = self.profile.hbm_bytes
            slices = range(self.placement_start, self.placement_start + cores)
        else:
            cores = self.chip.cores
            hbm = self.chip.hbm_bytes
            slices = range(self.chip.cores)
        counters = {
            "tensorcores": {"value": str(cores)},
            "hbm": {"value": str(hbm)},
        }
        for s in slices:
            counters[f"memory-slice-{s}"] = {"value": "1"}
        return counters

    def counter_set_name(self) -> str:
        return chip_counter_set_name(self.chip.index)


def chip_counter_set_name(chip_index: int) -> str:
    return f"tpu-{chip_index}-counter-set"


def chip_counter_set(chip: ChipInfo) -> Dict:
    """The shared CounterSet for one chip (reference partitions.go: one
    CounterSet per GPU with capacity counters + one memory-slice counter
    per slice)."""
    counters: Dict[str, Dict] = {
        "tensorcores": {"value": str(chip.cores)},
        "hbm": {"value": str(chip.hbm_bytes)},
    }
    for s in range(chip.cores):
        counters[f"memory-slice-{s}"] = {"value": "1"}
    return {"name": chip_counter_set_name(chip.index), "counters": counters}


def enumerate_allocatable(lib: TpuLib, gates: fg.FeatureGates
                          ) -> Dict[str, AllocatableDevice]:
    """Build the full allocatable-device map for this node.

    Reference analog: nvlib.go:170-310 (enumerateAllPossibleDevices).
    Chips currently bound to vfio are advertised *only* as VFIO devices
    (their runtime-driver device node is gone); with Passthrough enabled,
    unbound chips are advertised both ways and the scheduler's counter
    model keeps them mutually exclusive.
    """
    out: Dict[str, AllocatableDevice] = {}
    passthrough = gates.enabled(fg.PASSTHROUGH_SUPPORT)
    dynamic = gates.enabled(fg.DYNAMIC_SUBSLICE)
    for chip in lib.enumerate_chips():
        if chip.vfio_group is not None:
            # already flipped to vfio: only the passthrough personality
            dev = AllocatableDevice(DeviceType.VFIO, chip)
            out[dev.canonical_name] = dev
            continue
        dev = AllocatableDevice(DeviceType.CHIP, chip)
        out[dev.canonical_name] = dev
        if dynamic:
            for prof in profiles_for(chip.generation):
                if prof.cores == chip.generation.cores_per_chip:
                    continue  # full-chip profile == the chip device itself
                for start in prof.placements():
                    ss = AllocatableDevice(DeviceType.SUBSLICE, chip,
                                           profile=prof, placement_start=start)
                    out[ss.canonical_name] = ss
        if passthrough:
            vf = AllocatableDevice(DeviceType.VFIO, chip)
            out[vf.canonical_name] = vf
    return out


def _semverish(v: str) -> str:
    """Extract a semver-ish token for the 'version' typed attribute."""
    for tok in v.split():
        if tok and tok[0].isdigit():
            parts = (tok.split(".") + ["0", "0"])[:3]
            if all(p.split("-")[0].isdigit() for p in parts[:2]):
                return ".".join(parts)
    return "0.0.0"
