"""The allocatable-device model: what this node can offer the scheduler.

Reference analog: cmd/gpu-kubelet-plugin/{allocatable.go:39-44,
deviceinfo.go:113-241, mig.go:98-131} — ``AllocatableDevice`` is a tagged
union (Gpu | MigDynamic | MigStatic | Vfio) keyed by canonical name. Here:

- ``CHIP``      — a whole TPU chip (``tpu-<index>``),
- ``SUBSLICE``  — an *abstract* dynamically-creatable sub-slice
  (``tpu-<index>-ss-<profile>-<start>``): advertised always, created only
  when a claim lands (the DynamicMIG model),
- ``PROFILE``   — a *creatable profile slot* (``tpu-<index>-prof-<id>-<k>``,
  DynamicRepartition): the scheduler picks a slot, the kubelet plugin picks
  the concrete placement at prepare time and creates the partition on
  demand — the reference's DynamicMIG profile advertising, one step more
  abstract than pre-cut placements,
- ``SHARED``    — one multi-process client seat on a shared chip
  (``tpu-<index>-mp-<k>``, SharedChipServing): the claim-per-request
  serving unit with a fixed per-seat HBM budget,
- ``VFIO``      — a chip offered for passthrough (``tpu-vfio-<index>``).

Each device renders to a DRA device entry with typed attributes, capacity,
and (for KEP-4815 layouts) counter consumption against its chip's
CounterSet. With SharedChipServing the per-core ``memory-slice`` counters
are sub-divided into ``SEAT_COUNT/cores`` units per core so seats and
partitions exclude each other *per core* while distinct cores compose.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Dict, Optional

from tpu_dra_driver.pkg import featuregates as fg
from tpu_dra_driver.tpulib.interface import ChipInfo, TpuLib
from tpu_dra_driver.tpulib.partition import (
    SEAT_COUNT,
    SubsliceProfile,
    canonical_chip_name,
    canonical_profile_name,
    canonical_shared_name,
    canonical_subslice_name,
    canonical_vfio_name,
    profiles_for,
    seat_core,
    seats_per_core,
)

#: each seat's fixed HBM budget, in percent of the chip (the published
#: capacity is a static contract; claims do not negotiate it upward).
SEAT_HBM_PERCENT = 100 // SEAT_COUNT


class DeviceType(Enum):
    CHIP = "chip"
    SUBSLICE = "subslice"
    PROFILE = "profile"
    SHARED = "shared"
    VFIO = "vfio"


@dataclass(frozen=True)
class AllocatableDevice:
    type: DeviceType
    chip: ChipInfo
    profile: Optional[SubsliceProfile] = None    # SUBSLICE / PROFILE only
    placement_start: int = 0                     # SUBSLICE only
    slot: int = 0                                # PROFILE / SHARED only

    @property
    def canonical_name(self) -> str:
        if self.type == DeviceType.CHIP:
            return canonical_chip_name(self.chip.index)
        if self.type == DeviceType.SUBSLICE:
            assert self.profile is not None
            return canonical_subslice_name(self.chip.index, self.profile,
                                           self.placement_start)
        if self.type == DeviceType.PROFILE:
            assert self.profile is not None
            return canonical_profile_name(self.chip.index, self.profile,
                                          self.slot)
        if self.type == DeviceType.SHARED:
            return canonical_shared_name(self.chip.index, self.slot)
        return canonical_vfio_name(self.chip.index)

    # -- DRA rendering ------------------------------------------------------

    def attributes(self) -> Dict[str, Dict]:
        """Typed DRA attributes (reference deviceinfo.go:159-241 publishes
        type/uuid/productName/architecture/pciBusID/pcieRoot/driverVersion;
        TPU adds torus coords + slice identity, which is what topology-aware
        scheduling selects on)."""
        c = self.chip
        attrs: Dict[str, Dict] = {
            "type": {"string": self.type.value},
            "uuid": {"string": c.uuid},
            "productName": {"string": c.product_name},
            "generation": {"string": c.generation.name},
            "pciBusID": {"string": c.pci_address},
            "pcieRoot": {"string": c.pci_root},
            "driverVersion": {"version": _semverish(c.driver_version)},
            "firmwareVersion": {"string": c.firmware_version},
            "sliceID": {"string": c.slice_id},
            "hostIndex": {"int": c.host_index},
            "iciBandwidthGbps": {"int": c.generation.ici_bandwidth_gbps},
        }
        for dim, val in zip(("coordX", "coordY", "coordZ"), c.coords):
            attrs[dim] = {"int": val}
        if self.type == DeviceType.SUBSLICE:
            assert self.profile is not None
            attrs["profile"] = {"string": self.profile.id}
            attrs["placementStart"] = {"int": self.placement_start}
        if self.type == DeviceType.PROFILE:
            assert self.profile is not None
            attrs["profile"] = {"string": self.profile.id}
            attrs["slot"] = {"int": self.slot}
        if self.type == DeviceType.SHARED:
            attrs["seat"] = {"int": self.slot}
            attrs["seatCore"] = {"int": seat_core(self.slot,
                                                  self.chip.cores)}
        if self.type == DeviceType.VFIO:
            attrs["vfio"] = {"bool": True}
        return attrs

    @property
    def seat_hbm_bytes(self) -> int:
        """One seat's fixed HBM budget (SHARED only)."""
        return self.chip.hbm_bytes * SEAT_HBM_PERCENT // 100

    def capacity(self) -> Dict[str, Dict]:
        if self.type in (DeviceType.SUBSLICE, DeviceType.PROFILE):
            assert self.profile is not None
            cores = self.profile.cores
            hbm = self.profile.hbm_bytes
        elif self.type == DeviceType.SHARED:
            # a seat owns no core — it is one bounded client's HBM share
            return {"hbm": {"value": str(self.seat_hbm_bytes)}}
        else:
            cores = self.chip.cores
            hbm = self.chip.hbm_bytes
        return {
            "tensorcores": {"value": str(cores)},
            "hbm": {"value": str(hbm)},
        }

    def counter_consumption(self, granularity: int = 1) -> Dict[str, Dict]:
        """KEP-4815: counters this device consumes from its chip's
        CounterSet. The full chip consumes *everything*, a sub-slice its
        cores + per-core memory slices — making chip and overlapping
        sub-slice allocations mutually exclusive for the scheduler
        (reference partitions.go:27-215).

        ``granularity`` is the per-core memory-slice counter resolution
        (SharedChipServing sub-divides each core's counter into
        ``seats_per_core`` units): core-owning devices consume the FULL
        granularity of every covered slice, a SHARED seat consumes one
        unit of its core's slice — so seats and partitions exclude each
        other per core while distinct cores compose. A PROFILE slot
        consumes cores + HBM but no specific slice (its placement is
        picked at prepare time); the repartition placement picker honors
        the per-core occupancy the counters admitted."""
        if self.type == DeviceType.SHARED:
            return {
                "hbm": {"value": str(self.seat_hbm_bytes)},
                f"memory-slice-{seat_core(self.slot, self.chip.cores)}":
                    {"value": "1"},
            }
        if self.type in (DeviceType.SUBSLICE, DeviceType.PROFILE):
            assert self.profile is not None
            cores = self.profile.cores
            hbm = self.profile.hbm_bytes
            slices = (range(self.placement_start,
                            self.placement_start + cores)
                      if self.type == DeviceType.SUBSLICE else ())
        else:
            cores = self.chip.cores
            hbm = self.chip.hbm_bytes
            slices = range(self.chip.cores)
        counters = {
            "tensorcores": {"value": str(cores)},
            "hbm": {"value": str(hbm)},
        }
        for s in slices:
            counters[f"memory-slice-{s}"] = {"value": str(granularity)}
        return counters

    def counter_set_name(self) -> str:
        return chip_counter_set_name(self.chip.index)


def chip_counter_set_name(chip_index: int) -> str:
    return f"tpu-{chip_index}-counter-set"


def chip_counter_set(chip: ChipInfo, granularity: int = 1) -> Dict:
    """The shared CounterSet for one chip (reference partitions.go: one
    CounterSet per GPU with capacity counters + one memory-slice counter
    per slice). ``granularity`` sub-divides each core's memory-slice
    counter (SharedChipServing seat units)."""
    counters: Dict[str, Dict] = {
        "tensorcores": {"value": str(chip.cores)},
        "hbm": {"value": str(chip.hbm_bytes)},
    }
    for s in range(chip.cores):
        counters[f"memory-slice-{s}"] = {"value": str(granularity)}
    return {"name": chip_counter_set_name(chip.index), "counters": counters}


def enumerate_allocatable(lib: TpuLib, gates: fg.FeatureGates
                          ) -> Dict[str, AllocatableDevice]:
    """Build the full allocatable-device map for this node.

    Reference analog: nvlib.go:170-310 (enumerateAllPossibleDevices).
    Chips currently bound to vfio are advertised *only* as VFIO devices
    (their runtime-driver device node is gone); with Passthrough enabled,
    unbound chips are advertised both ways and the scheduler's counter
    model keeps them mutually exclusive.
    """
    out: Dict[str, AllocatableDevice] = {}
    passthrough = gates.enabled(fg.PASSTHROUGH_SUPPORT)
    dynamic = gates.enabled(fg.DYNAMIC_SUBSLICE)
    repartition = gates.enabled(fg.DYNAMIC_REPARTITION)
    shared = gates.enabled(fg.SHARED_CHIP_SERVING)
    for chip in lib.enumerate_chips():
        if chip.vfio_group is not None:
            # already flipped to vfio: only the passthrough personality
            dev = AllocatableDevice(DeviceType.VFIO, chip)
            out[dev.canonical_name] = dev
            continue
        dev = AllocatableDevice(DeviceType.CHIP, chip)
        out[dev.canonical_name] = dev
        if dynamic:
            for prof in profiles_for(chip.generation):
                if prof.cores == chip.generation.cores_per_chip:
                    continue  # full-chip profile == the chip device itself
                for start in prof.placements():
                    ss = AllocatableDevice(DeviceType.SUBSLICE, chip,
                                           profile=prof, placement_start=start)
                    out[ss.canonical_name] = ss
        if repartition:
            # creatable profile slots: one anonymous slot per possible
            # concurrent placement of the profile — the scheduler admits
            # capacity, the plugin picks WHERE at prepare time
            for prof in profiles_for(chip.generation):
                if prof.cores == chip.generation.cores_per_chip:
                    continue
                for k in range(len(prof.placements())):
                    ps = AllocatableDevice(DeviceType.PROFILE, chip,
                                           profile=prof, slot=k)
                    out[ps.canonical_name] = ps
        if shared:
            for k in range(SEAT_COUNT):
                seat = AllocatableDevice(DeviceType.SHARED, chip, slot=k)
                out[seat.canonical_name] = seat
        if passthrough:
            vf = AllocatableDevice(DeviceType.VFIO, chip)
            out[vf.canonical_name] = vf
    return out


def _semverish(v: str) -> str:
    """Extract a semver-ish token for the 'version' typed attribute."""
    for tok in v.split():
        if tok and tok[0].isdigit():
            parts = (tok.split(".") + ["0", "0"])[:3]
            if all(p.split("-")[0].isdigit() for p in parts[:2]):
                return ".".join(parts)
    return "0.0.0"
