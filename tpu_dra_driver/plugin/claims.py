"""DRA ResourceClaim helpers: allocation results and opaque-config resolution.

Reference analog: cmd/gpu-kubelet-plugin/device_state.go:1019-1072
(GetOpaqueDeviceConfigs) and types.go:48-70 (canonical claim strings).

A ResourceClaim (dict form, resource.k8s.io shape) carries, once allocated::

    status.allocation.devices.results[]: {request, driver, pool, device}
    status.allocation.devices.config[]:  {source: "FromClass"|"FromClaim",
                                          requests: [...],
                                          opaque: {driver, parameters}}

Config precedence: class configs apply first, claim configs override them
(the reference appends class configs, then claim configs, and the *last*
matching config for a result wins).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra_driver import DRIVER_NAME
from tpu_dra_driver.api.decoder import Decoder, DecodeError

SOURCE_CLASS = "FromClass"
SOURCE_CLAIM = "FromClaim"


@dataclass(frozen=True)
class AllocationResult:
    request: str
    driver: str
    pool: str
    device: str            # canonical device name
    admin_access: bool = False


@dataclass
class ClaimInfo:
    uid: str
    name: str
    namespace: str
    results: List[AllocationResult] = field(default_factory=list)
    configs: List[Dict] = field(default_factory=list)  # raw allocation configs

    @property
    def canonical(self) -> str:
        """``ns/name:uid`` — the canonical claim string used in every log
        line and error (reference types.go:48-70)."""
        return f"{self.namespace}/{self.name}:{self.uid}"

    @staticmethod
    def from_obj(obj: Dict, driver_name: str = DRIVER_NAME) -> "ClaimInfo":
        meta = obj.get("metadata") or {}
        alloc = ((obj.get("status") or {}).get("allocation") or {})
        devices = alloc.get("devices") or {}
        results = []
        for r in devices.get("results") or []:
            if r.get("driver") != driver_name:
                continue
            results.append(AllocationResult(
                request=r.get("request", ""),
                driver=r.get("driver", ""),
                pool=r.get("pool", ""),
                device=r.get("device", ""),
                admin_access=bool(r.get("adminAccess", False)),
            ))
        return ClaimInfo(
            uid=meta.get("uid", ""),
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            results=results,
            configs=list(devices.get("config") or []),
        )

    @staticmethod
    def from_objs(objs: List[Dict],
                  driver_name: str = DRIVER_NAME) -> List["ClaimInfo"]:
        """Batch form of :meth:`from_obj`: one kubelet
        NodePrepareResources call decodes every claim up front, so the
        group-commit prepare path can take the whole batch under a
        single lock acquisition."""
        return [ClaimInfo.from_obj(obj, driver_name) for obj in objs]


@dataclass
class ResolvedConfig:
    """An opaque config resolved for a specific set of requests."""

    source: str
    requests: List[str]
    config: object  # decoded api config object


def resolve_opaque_configs(claim: ClaimInfo, decoder: Decoder,
                           driver_name: str = DRIVER_NAME) -> List[ResolvedConfig]:
    """Decode + order opaque configs: FromClass first, FromClaim second, so
    later (claim-level) configs override class defaults when both match a
    request (reference device_state.go:1019-1072)."""
    ordered = (
        [c for c in claim.configs if c.get("source") == SOURCE_CLASS]
        + [c for c in claim.configs if c.get("source") == SOURCE_CLAIM]
    )
    out: List[ResolvedConfig] = []
    for c in ordered:
        opaque = c.get("opaque")
        if not opaque or opaque.get("driver") != driver_name:
            continue
        params = opaque.get("parameters")
        if params is None:
            raise DecodeError("opaque config missing parameters")
        cfg = decoder.decode(params)
        cfg.normalize()
        cfg.validate()
        out.append(ResolvedConfig(
            source=c.get("source", ""),
            requests=list(c.get("requests") or []),
            config=cfg,
        ))
    return out


def config_for_result(configs: List[ResolvedConfig],
                      result: AllocationResult) -> Optional[ResolvedConfig]:
    """The effective config for one allocation result: the *last* config
    whose request list matches (or is empty = matches all)."""
    chosen: Optional[ResolvedConfig] = None
    for rc in configs:
        if not rc.requests or result.request in rc.requests:
            chosen = rc
    return chosen


def build_allocated_claim(uid: str, name: str, namespace: str,
                          device_names: List[str], node: str,
                          configs: Optional[List[Dict]] = None,
                          driver_name: str = DRIVER_NAME,
                          request: str = "tpu") -> Dict:
    """Test/demo helper: fabricate an allocated ResourceClaim dict the way
    the scheduler would after satisfying a request against our slices."""
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": namespace, "uid": uid},
        "spec": {"devices": {"requests": [{"name": request}]}},
        "status": {
            "allocation": {
                "devices": {
                    "results": [
                        {"request": request, "driver": driver_name,
                         "pool": node, "device": d}
                        for d in device_names
                    ],
                    "config": configs or [],
                },
                "nodeSelector": {"kubernetes.io/hostname": node},
            }
        },
    }
