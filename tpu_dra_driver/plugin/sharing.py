"""Chip-sharing managers: time-slicing and multi-process.

Reference analog: cmd/gpu-kubelet-plugin/sharing.go — TimeSlicingManager
(nvidia-smi compute-policy per GPU) and MpsManager (a per-claim MPS
control-daemon Deployment with shm/pipe/log host dirs).

TPU design departure (SURVEY.md §7.6): TPUs need **no control daemon** for
multi-process sharing — libtpu multiplexes clients itself when the right
env is present. So MultiProcessManager is pure CDI env injection:

- ``TPU_MULTI_PROCESS=1`` + per-client HBM ceiling
  (``TPU_HBM_LIMIT_PERCENT``, enforced by the runtime allocator) +
  ``TPU_MAX_CLIENTS``;
- the chip is flipped to non-exclusive mode via the device library.

TimeSlicingManager maps the interval enum onto the runtime scheduler knob
through the TpuLib seam (the ``nvidia-smi --set-timeslice`` analog).
"""

from __future__ import annotations

import threading
from typing import Dict, List

from tpu_dra_driver.api.configs import MultiProcessConfig, TimeSlicingConfig
from tpu_dra_driver.cdi.generator import ContainerEdits
from tpu_dra_driver.tpulib.interface import TimesliceInterval, TpuLib


class TimeSlicingManager:
    def __init__(self, lib: TpuLib):
        self._lib = lib
        self._mu = threading.Lock()

    def apply(self, chip_uuids: List[str], cfg: TimeSlicingConfig) -> ContainerEdits:
        interval = TimesliceInterval(cfg.interval)
        with self._mu:
            for uuid in chip_uuids:
                # time-slicing needs shared (non-exclusive) scheduling
                self._lib.set_exclusive_mode(uuid, False)
                self._lib.set_timeslice(uuid, interval)
        return ContainerEdits(env={
            "TPU_TIMESLICE_INTERVAL": cfg.interval,
        })

    def reset(self, chip_uuids: List[str]) -> None:
        """Restore the default interval on unprepare so sharing settings
        cannot leak into the next claim on the same chip."""
        with self._mu:
            for uuid in chip_uuids:
                self._lib.set_timeslice(uuid, TimesliceInterval.DEFAULT)


class MultiProcessManager:
    def __init__(self, lib: TpuLib):
        self._lib = lib
        self._mu = threading.Lock()

    def apply(self, chip_uuids: List[str], cfg: MultiProcessConfig) -> ContainerEdits:
        with self._mu:
            for uuid in chip_uuids:
                self._lib.set_exclusive_mode(uuid, False)
        env: Dict[str, str] = {
            "TPU_MULTI_PROCESS": "1",
            "TPU_MAX_CLIENTS": str(cfg.max_clients),
        }
        if cfg.hbm_limit_percent is not None:
            env["TPU_HBM_LIMIT_PERCENT"] = str(cfg.hbm_limit_percent)
        return ContainerEdits(env=env)

    def release(self, chip_uuids: List[str]) -> None:
        """Restore exclusive mode on unprepare (the reference's MPS daemon
        teardown analog; here only a mode flip)."""
        with self._mu:
            for uuid in chip_uuids:
                self._lib.set_exclusive_mode(uuid, True)
