"""Chip-sharing managers: time-slicing and multi-process.

Reference analog: cmd/gpu-kubelet-plugin/sharing.go — TimeSlicingManager
(nvidia-smi compute-policy per GPU) and MpsManager (a per-claim MPS
control-daemon Deployment with shm/pipe/log host dirs).

TPU design departure (SURVEY.md §7.6): TPUs need **no control daemon** for
multi-process sharing — libtpu multiplexes clients itself when the right
env is present. MultiProcessManager therefore has two jobs:

- **grant bookkeeping through the device library's share ledger**
  (``allocate_multiprocess_share``): rejects over-subscribed configs
  (clients x per-client HBM > chip) and double-grants as *permanent*
  errors, persists the grant so a crashed plugin's share is released on
  unprepare, and sizes the per-client HBM budget in bytes — the
  enforcement-accounting half of the reference's MPS control daemon
  (sharing.go:151-436). The fake backend models client connections and
  per-client HBM budgets so tests prove the limits bind.
- **CDI env injection**: ``TPU_MULTI_PROCESS=1``, ``TPU_MAX_CLIENTS``,
  per-client ``TPU_HBM_LIMIT_PERCENT``/``TPU_HBM_LIMIT_BYTES`` (the
  runtime allocator reads these); the chip is flipped to non-exclusive
  mode via the device library.

TimeSlicingManager maps the interval enum onto the runtime scheduler knob
through the TpuLib seam (the ``nvidia-smi --set-timeslice`` analog).
"""

from __future__ import annotations

import threading
from typing import Dict, List

from tpu_dra_driver.api.configs import MultiProcessConfig, TimeSlicingConfig
from tpu_dra_driver.cdi.generator import ContainerEdits
from tpu_dra_driver.pkg.metrics import SHARED_CHIP_CLIENTS
from tpu_dra_driver.tpulib.interface import TimesliceInterval, TpuLib


class TimeSlicingManager:
    def __init__(self, lib: TpuLib):
        self._lib = lib
        self._mu = threading.Lock()

    def apply(self, chip_uuids: List[str], cfg: TimeSlicingConfig) -> ContainerEdits:
        interval = TimesliceInterval(cfg.interval)
        with self._mu:
            for uuid in chip_uuids:
                # time-slicing needs shared (non-exclusive) scheduling
                self._lib.set_exclusive_mode(uuid, False)
                self._lib.set_timeslice(uuid, interval)
        return ContainerEdits(env={
            "TPU_TIMESLICE_INTERVAL": cfg.interval,
        })

    def reset(self, chip_uuids: List[str]) -> None:
        """Restore default scheduling on unprepare so sharing settings
        cannot leak into the next claim on the same chip — BOTH the
        interval and exclusive mode: ``apply`` flipped the chip
        non-exclusive, so a reset that only restored the interval left a
        later exclusive claim silently running shared (the sharing-mode
        leak this method's regression test pins)."""
        with self._mu:
            for uuid in chip_uuids:
                self._lib.set_timeslice(uuid, TimesliceInterval.DEFAULT)
                self._lib.set_exclusive_mode(uuid, True)


class MultiProcessManager:
    def __init__(self, lib: TpuLib):
        self._lib = lib
        self._mu = threading.Lock()

    def apply(self, chip_uuids: List[str], cfg: MultiProcessConfig,
              owner: str) -> ContainerEdits:
        """Grant the claim's share on every chip, then inject the client
        env. SharingExhaustedError (over-subscription, foreign share)
        propagates as a permanent prepare failure; a grant failure on a
        later chip rolls back earlier grants so nothing leaks."""
        pct = cfg.hbm_limit_percent if cfg.hbm_limit_percent is not None else 100
        granted = []
        with self._mu:
            try:
                share = None
                for uuid in chip_uuids:
                    share = self._lib.allocate_multiprocess_share(
                        uuid, owner, cfg.max_clients, pct)
                    granted.append(uuid)
                    self._lib.set_exclusive_mode(uuid, False)
            except Exception:
                for uuid in granted:
                    self._lib.release_multiprocess_share(uuid, owner)
                    self._lib.set_exclusive_mode(uuid, True)
                raise
        env: Dict[str, str] = {
            "TPU_MULTI_PROCESS": "1",
            "TPU_MAX_CLIENTS": str(cfg.max_clients),
        }
        if cfg.hbm_limit_percent is not None:
            env["TPU_HBM_LIMIT_PERCENT"] = str(cfg.hbm_limit_percent)
        if share is not None:
            env["TPU_HBM_LIMIT_BYTES"] = str(share.client_hbm_bytes)
        return ContainerEdits(env=env)

    def release(self, chip_uuids: List[str]) -> None:
        """Release the chips' shares and restore exclusive mode on
        unprepare (the reference's MPS daemon teardown analog)."""
        with self._mu:
            for uuid in chip_uuids:
                self._lib.release_multiprocess_share(uuid)
                self._lib.set_exclusive_mode(uuid, True)

    # -- per-claim client seats (SharedChipServing) ------------------------

    def attach_seat(self, chip_uuid: str, seat: int, owner: str,
                    hbm_limit_percent: int) -> ContainerEdits:
        """Attach ONE client seat on a shared chip for ``owner`` (the
        claim-per-request serving unit) and inject the bounded-client
        env. Raises SharingExhaustedError for seat conflicts /
        over-subscription / a partitioned core — a permanent failure for
        this claim."""
        with self._mu:
            before = len(self._lib.list_multiprocess_seats(chip_uuid))
            share = self._lib.attach_multiprocess_seat(
                chip_uuid, owner, seat, hbm_limit_percent)
            self._lib.set_exclusive_mode(chip_uuid, False)
            after = len(self._lib.list_multiprocess_seats(chip_uuid))
            # delta, not unconditional: an idempotent re-attach (kubelet
            # retrying a partially-failed prepare) returns the existing
            # share and must not inflate the density gauge
            if after > before:
                SHARED_CHIP_CLIENTS.inc(after - before)
        return ContainerEdits(env={
            "TPU_MULTI_PROCESS": "1",
            "TPU_MP_SEAT": str(seat),
            "TPU_HBM_LIMIT_PERCENT": str(share.hbm_limit_percent),
            "TPU_HBM_LIMIT_BYTES": str(share.client_hbm_bytes),
        })

    def detach_seat(self, chip_uuid: str, owner: str) -> None:
        """Detach the claim's seat(s) on unprepare; the chip returns to
        exclusive scheduling only once its LAST seat detaches (other
        claims' clients keep running)."""
        with self._mu:
            before = len(self._lib.list_multiprocess_seats(chip_uuid))
            self._lib.detach_multiprocess_seat(chip_uuid, owner=owner)
            after = len(self._lib.list_multiprocess_seats(chip_uuid))
            if before > after:
                SHARED_CHIP_CLIENTS.dec(before - after)
            if after == 0:
                self._lib.set_exclusive_mode(chip_uuid, True)
