"""ResourceSlice generation, including KEP-4815 partitionable layouts.

Reference analog: cmd/gpu-kubelet-plugin/driver.go:177-268,507-540 — the
driver publishes its allocatable devices as ResourceSlices in one of two
layouts depending on the API server's KEP-4815 maturity:

- **combined** (k8s 1.34): a single slice carrying both the SharedCounters
  and every device;
- **split** (k8s ≥1.35): one slice holding only the SharedCounters, plus
  one slice per chip holding that chip's devices (keeps slice churn local
  to a chip when health events hide devices).

Slices live in a per-node pool named after the node. Publishing is
**churn-free** at scale:

- ``republish()`` content-compares each desired slice against what the
  API server already holds and SKIPS no-op writes (counted in
  ``dra_resourceslice_publishes_skipped_total``) — a republish that
  changes nothing performs zero API writes;
- the pool generation bumps only when the slice COMPOSITION changes
  (names or count — which forces every slice to be rewritten under the
  new generation, since the scheduler discards slices below the pool's
  max generation); a content-only change keeps the generation and
  rewrites just the changed slice;
- above ``max_devices_per_slice`` the combined layout splits its device
  list over multiple slices with STABLE name assignment: devices are
  bucketed by their position in the full (pre-exclusion) inventory, so
  hiding one unhealthy device rewrites that device's slice, not the
  whole pool.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from tpu_dra_driver import DRIVER_NAME
from tpu_dra_driver.kube.client import ResourceClient
from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg.metrics import (
    RESOURCESLICE_PUBLISHES,
    RESOURCESLICE_PUBLISHES_SKIPPED,
)
from tpu_dra_driver.plugin.allocatable import (
    AllocatableDevice,
    DeviceType,
    chip_counter_set,
    seats_per_core,
)

fi.register("resourceslice.publish",
            "each ResourceSlice API write (create/update/delete) in "
            "republish() (fail models the API server rejecting a slice "
            "write mid-republish; the next republish must converge)")

LAYOUT_COMBINED = "combined"
LAYOUT_SPLIT = "split"


def _device_entry(dev: AllocatableDevice, with_counters: bool,
                  node_name: str = "", granularity: int = 1) -> Dict:
    entry: Dict = {
        "name": dev.canonical_name,
        "attributes": dev.attributes(),
        "capacity": dev.capacity(),
    }
    if node_name:
        # node identity as a selectable attribute: DRA CEL selectors
        # cannot reach spec.pool/nodeName, so node-targeted claims (the
        # drain/churn scenarios, operators pinning diagnostics jobs)
        # need it ON the device — and the catalog indexes it, making
        # node-pinned claims an O(own-devices) index probe
        entry["attributes"] = {**entry["attributes"],
                               "node": {"string": node_name}}
    if with_counters:
        entry["consumesCounters"] = [{
            "counterSet": dev.counter_set_name(),
            "counters": dev.counter_consumption(granularity),
        }]
    return entry


def _chip_granularities(devices: Dict[str, AllocatableDevice]
                        ) -> Dict[int, int]:
    """Per-chip memory-slice counter resolution: chips advertising SHARED
    seats sub-divide each core's counter into seat units so seats and
    core-owning devices exclude per core; everyone else stays at 1. Uses
    the FULL inventory (not the visible subset) so exclusions cannot flip
    a chip's counter granularity mid-lifecycle."""
    out: Dict[int, int] = {}
    for d in devices.values():
        idx = d.chip.index
        if d.type == DeviceType.SHARED:
            out[idx] = seats_per_core(d.chip.cores)
        else:
            out.setdefault(idx, 1)
    return out


def build_resource_slices(node_name: str,
                          devices: Dict[str, AllocatableDevice],
                          layout: str = LAYOUT_COMBINED,
                          generation: int = 1,
                          exclude: Optional[Set[str]] = None,
                          partitionable: bool = True,
                          max_devices_per_slice: int = 0) -> List[Dict]:
    """Render slices for the given allocatable devices.

    ``exclude`` removes devices (unhealthy, or hidden vfio siblings) without
    touching the rest. Counter sets are emitted only when ``partitionable``
    (i.e. DynamicSubslice active) — whole-chip-only inventories don't need
    the counter machinery. ``max_devices_per_slice`` > 0 chunks the
    combined layout's device list over multiple slices; bucket assignment
    uses the FULL inventory order (exclusions leave a hole in their own
    bucket instead of shifting every later device into a different slice).
    """
    exclude = exclude or set()
    visible = {n: d for n, d in devices.items() if n not in exclude}
    chips = sorted({d.chip.index: d.chip for d in visible.values()}.items())
    grans = _chip_granularities(devices)
    counter_sets = ([chip_counter_set(chip, grans.get(idx, 1))
                     for idx, chip in chips] if partitionable else [])

    def slice_obj(name: str, devs: List[Dict], shared: List[Dict],
                  count: int) -> Dict:
        spec: Dict = {
            "driver": DRIVER_NAME,
            "nodeName": node_name,
            "pool": {
                "name": node_name,
                "generation": generation,
                "resourceSliceCount": count,
            },
            "devices": devs,
        }
        if shared:
            spec["sharedCounters"] = shared
        return {
            "apiVersion": "resource.k8s.io/v1beta1",
            "kind": "ResourceSlice",
            "metadata": {"name": name},
            "spec": spec,
        }

    ordered = [visible[k] for k in sorted(visible)]
    if layout == LAYOUT_COMBINED or not partitionable:
        limit = max_devices_per_slice
        if limit and len(devices) > limit:
            # stable chunking over the FULL inventory: bucket i holds the
            # devices at positions [i*limit, (i+1)*limit) of the sorted
            # complete device list, minus exclusions — so a health event
            # on one device dirties exactly one slice. The counters slice
            # exists only when there are counter sets to carry.
            all_names = sorted(devices)
            buckets = [all_names[i:i + limit]
                       for i in range(0, len(all_names), limit)]
            count = (1 if counter_sets else 0) + len(buckets)
            out = []
            if counter_sets:
                out.append(slice_obj(f"{node_name}-{DRIVER_NAME}-counters",
                                     [], counter_sets, count))
            for i, bucket in enumerate(buckets):
                devs = [_device_entry(devices[n], partitionable, node_name,
                                      grans.get(devices[n].chip.index, 1))
                        for n in bucket if n in visible]
                out.append(slice_obj(f"{node_name}-{DRIVER_NAME}-p{i}",
                                     devs, [], count))
            return out
        return [slice_obj(
            f"{node_name}-{DRIVER_NAME}",
            [_device_entry(d, partitionable, node_name,
                           grans.get(d.chip.index, 1)) for d in ordered],
            counter_sets, 1,
        )]

    # split layout: counters slice + one device slice per chip
    out = []
    count = 1 + len(chips)
    out.append(slice_obj(f"{node_name}-{DRIVER_NAME}-counters", [],
                         counter_sets, count))
    for chip_idx, _ in chips:
        devs = [_device_entry(d, True, node_name,
                              grans.get(chip_idx, 1))
                for d in ordered if d.chip.index == chip_idx]
        out.append(slice_obj(f"{node_name}-{DRIVER_NAME}-chip{chip_idx}",
                             devs, [], count))
    return out


class ResourceSlicePublisher:
    """Owns this node's slice pool in the API server: republish() diffs the
    desired set against what exists (create/update/delete by name) — the
    kubeletplugin.PublishResources analog — skipping writes whose content
    is already published and bumping the pool generation only when the
    slice composition changes."""

    def __init__(self, client: ResourceClient, node_name: str,
                 layout: str = LAYOUT_COMBINED,
                 max_devices_per_slice: int = 0):
        self._client = client
        self._node = node_name
        self._layout = layout
        self._max_devices_per_slice = max_devices_per_slice
        self._mu = threading.Lock()
        self._generation = 0

    def _existing(self) -> Dict[str, Dict]:
        return {
            o["metadata"]["name"]: o
            for o in self._client.list()
            if o["spec"].get("nodeName") == self._node
            and o["spec"].get("driver") == DRIVER_NAME
        }

    def republish(self, devices: Dict[str, AllocatableDevice],
                  exclude: Optional[Set[str]] = None,
                  partitionable: bool = True) -> List[Dict]:
        with self._mu:
            existing = self._existing()
            if self._generation == 0:
                # adopt the live pool's generation across restarts so the
                # first republish after a content-only change stays
                # churn-free
                self._generation = max(
                    (o["spec"].get("pool", {}).get("generation", 0)
                     for o in existing.values()), default=0) or 1

            desired = build_resource_slices(
                self._node, devices, layout=self._layout,
                generation=self._generation, exclude=exclude,
                partitionable=partitionable,
                max_devices_per_slice=self._max_devices_per_slice,
            )
            # composition change (slice names appearing/disappearing)
            # invalidates the whole pool: bump the generation — the
            # scheduler ignores slices below the pool max, so EVERY slice
            # must be rewritten under the new generation
            if {o["metadata"]["name"] for o in desired} != set(existing):
                self._generation += 1
                for obj in desired:
                    obj["spec"]["pool"]["generation"] = self._generation

            for obj in desired:
                name = obj["metadata"]["name"]
                if name in existing:
                    cur = existing.pop(name)
                    if cur.get("spec") == obj["spec"]:
                        RESOURCESLICE_PUBLISHES_SKIPPED.inc()
                        continue
                    fi.fire("resourceslice.publish")
                    cur["spec"] = obj["spec"]
                    self._client.update(cur)
                    RESOURCESLICE_PUBLISHES.labels("update").inc()
                else:
                    fi.fire("resourceslice.publish")
                    self._client.create(obj)
                    RESOURCESLICE_PUBLISHES.labels("create").inc()
            for leftover in existing:
                fi.fire("resourceslice.publish")
                self._client.delete_ignore_missing(leftover)
                RESOURCESLICE_PUBLISHES.labels("delete").inc()
            return desired
