"""ResourceSlice generation, including KEP-4815 partitionable layouts.

Reference analog: cmd/gpu-kubelet-plugin/driver.go:177-268,507-540 — the
driver publishes its allocatable devices as ResourceSlices in one of two
layouts depending on the API server's KEP-4815 maturity:

- **combined** (k8s 1.34): a single slice carrying both the SharedCounters
  and every device;
- **split** (k8s ≥1.35): one slice holding only the SharedCounters, plus
  one slice per chip holding that chip's devices (keeps slice churn local
  to a chip when health events hide devices).

Slices live in a per-node pool named after the node; the pool generation
bumps on every republish so the scheduler discards stale slices.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from tpu_dra_driver import DRIVER_NAME
from tpu_dra_driver.kube.client import ResourceClient
from tpu_dra_driver.plugin.allocatable import (
    AllocatableDevice,
    chip_counter_set,
)

LAYOUT_COMBINED = "combined"
LAYOUT_SPLIT = "split"


def _device_entry(dev: AllocatableDevice, with_counters: bool) -> Dict:
    entry: Dict = {
        "name": dev.canonical_name,
        "attributes": dev.attributes(),
        "capacity": dev.capacity(),
    }
    if with_counters:
        entry["consumesCounters"] = [{
            "counterSet": dev.counter_set_name(),
            "counters": dev.counter_consumption(),
        }]
    return entry


def build_resource_slices(node_name: str,
                          devices: Dict[str, AllocatableDevice],
                          layout: str = LAYOUT_COMBINED,
                          generation: int = 1,
                          exclude: Optional[Set[str]] = None,
                          partitionable: bool = True) -> List[Dict]:
    """Render slices for the given allocatable devices.

    ``exclude`` removes devices (unhealthy, or hidden vfio siblings) without
    touching the rest. Counter sets are emitted only when ``partitionable``
    (i.e. DynamicSubslice active) — whole-chip-only inventories don't need
    the counter machinery.
    """
    exclude = exclude or set()
    visible = {n: d for n, d in devices.items() if n not in exclude}
    chips = sorted({d.chip.index: d.chip for d in visible.values()}.items())
    counter_sets = [chip_counter_set(chip) for _, chip in chips] if partitionable else []

    def slice_obj(name: str, devs: List[Dict], shared: List[Dict],
                  count: int) -> Dict:
        spec: Dict = {
            "driver": DRIVER_NAME,
            "nodeName": node_name,
            "pool": {
                "name": node_name,
                "generation": generation,
                "resourceSliceCount": count,
            },
            "devices": devs,
        }
        if shared:
            spec["sharedCounters"] = shared
        return {
            "apiVersion": "resource.k8s.io/v1beta1",
            "kind": "ResourceSlice",
            "metadata": {"name": name},
            "spec": spec,
        }

    ordered = [visible[k] for k in sorted(visible)]
    if layout == LAYOUT_COMBINED or not partitionable:
        return [slice_obj(
            f"{node_name}-{DRIVER_NAME}",
            [_device_entry(d, partitionable) for d in ordered],
            counter_sets, 1,
        )]

    # split layout: counters slice + one device slice per chip
    out = []
    count = 1 + len(chips)
    out.append(slice_obj(f"{node_name}-{DRIVER_NAME}-counters", [],
                         counter_sets, count))
    for chip_idx, _ in chips:
        devs = [_device_entry(d, True) for d in ordered if d.chip.index == chip_idx]
        out.append(slice_obj(f"{node_name}-{DRIVER_NAME}-chip{chip_idx}",
                             devs, [], count))
    return out


class ResourceSlicePublisher:
    """Owns this node's slice pool in the API server: republish() diffs the
    desired set against what exists (create/update/delete by name) under a
    bumped pool generation — the kubeletplugin.PublishResources analog."""

    def __init__(self, client: ResourceClient, node_name: str,
                 layout: str = LAYOUT_COMBINED):
        self._client = client
        self._node = node_name
        self._layout = layout
        self._mu = threading.Lock()
        self._generation = 0

    def republish(self, devices: Dict[str, AllocatableDevice],
                  exclude: Optional[Set[str]] = None,
                  partitionable: bool = True) -> List[Dict]:
        with self._mu:
            self._generation += 1
            desired = build_resource_slices(
                self._node, devices, layout=self._layout,
                generation=self._generation, exclude=exclude,
                partitionable=partitionable,
            )
            existing = {
                o["metadata"]["name"]: o
                for o in self._client.list()
                if o["spec"].get("nodeName") == self._node
                and o["spec"].get("driver") == DRIVER_NAME
            }
            for obj in desired:
                name = obj["metadata"]["name"]
                if name in existing:
                    cur = existing.pop(name)
                    cur["spec"] = obj["spec"]
                    self._client.update(cur)
                else:
                    self._client.create(obj)
            for leftover in existing:
                self._client.delete_ignore_missing(leftover)
            return desired
