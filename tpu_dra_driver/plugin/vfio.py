"""VFIO passthrough manager.

Reference analog: cmd/gpu-kubelet-plugin/vfio-device.go:33-307 +
scripts/bind_to_driver.sh — flip a device between the runtime driver and
vfio-pci via sysfs driver_override, guarded by: device-not-busy check
(fuser analog), per-chip mutex, and slice republish after each flip so
sibling personalities (chip vs vfio) are hidden/shown consistently.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from tpu_dra_driver.cdi.generator import ContainerEdits
from tpu_dra_driver.tpulib.interface import TpuLib, TpuLibError


class VfioBusyError(TpuLibError):
    pass


class VfioPciManager:
    def __init__(self, lib: TpuLib,
                 on_topology_change: Optional[Callable[[], None]] = None):
        self._lib = lib
        self._on_change = on_topology_change
        self._locks: Dict[str, threading.Lock] = {}
        self._mu = threading.Lock()

    def set_topology_change_callback(self, cb: Callable[[], None]) -> None:
        self._on_change = cb

    def _lock_for(self, pci: str) -> threading.Lock:
        with self._mu:
            return self._locks.setdefault(pci, threading.Lock())

    def configure(self, pci_address: str) -> str:
        """Bind to vfio-pci; returns the vfio group path for CDI injection."""
        with self._lock_for(pci_address):
            if self._lib.device_in_use(pci_address):
                raise VfioBusyError(
                    f"device {pci_address} is in use; refusing driver flip"
                )
            if self._lib.current_driver(pci_address) == "vfio-pci":
                chips = [c for c in self._lib.enumerate_chips()
                         if c.pci_address == pci_address]
                if chips and chips[0].vfio_group:
                    return chips[0].vfio_group
            group = self._lib.bind_to_vfio(pci_address)
        if self._on_change:
            self._on_change()
        return group

    def unconfigure(self, pci_address: str) -> None:
        with self._lock_for(pci_address):
            if self._lib.current_driver(pci_address) == "vfio-pci":
                self._lib.unbind_from_vfio(pci_address)
        if self._on_change:
            self._on_change()

    @staticmethod
    def container_edits(group_path: str) -> ContainerEdits:
        return ContainerEdits(
            env={"TPU_VFIO_GROUP": group_path},
            device_nodes=[
                {"path": "/dev/vfio/vfio"},
                {"path": group_path},
            ],
        )
