"""DeviceState: the checkpointed Prepare/Unprepare critical path.

Reference analog: cmd/gpu-kubelet-plugin/device_state.go — the semantics
ported wholesale (they encode hard-won crash-safety, SURVEY.md §2.5/§7.3):

1. all checkpoint access under a dedicated file lock (``cp.lock``),
2. idempotency: a claim already PrepareCompleted returns its cached devices,
3. overlap guard: a device in another claim's *completed* entry cannot be
   prepared again (admin-access claims exempt),
4. rollback: a leftover PrepareStarted entry from a crashed attempt is
   unprepared before retrying,
5. write-ahead: PrepareStarted is persisted *before* any device mutation,
   PrepareCompleted only after the CDI spec is on disk,
6. startup ``destroy_unknown_subslices`` tears down live partitions no
   completed claim owns (the DestroyUnknownMIGDevices analog).

Every prepare records a wall-time breadcrumb dict (the ``t_prep*`` klog
lines, device_state.go:180-282) — the data source for the
claim-to-ready benchmark in bench.py.

Unlike the reference's per-claim serial loop (driver.go:334-386), a
kubelet batch goes through ``prepare_batch``/``unprepare_batch``: one
lock acquisition, one checkpoint read, one write-ahead fsync and one
commit fsync for the WHOLE batch (2 checkpoint writes per batch instead
of 2 per claim), with per-claim error isolation. Semantics 1-6 above
are preserved exactly — a failed claim's PrepareStarted entry rides the
batch commit and is rolled back on retry/restart just as before.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterable, List, Optional, Set

from tpu_dra_driver.api.configs import (
    SubsliceConfig,
    TpuConfig,
    ValidationError,
    VfioTpuConfig,
)
from tpu_dra_driver.api.decoder import STRICT_DECODER, DecodeError
from tpu_dra_driver.cdi.generator import CdiDevice, CdiHandler, ContainerEdits
from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg import featuregates as fg
from tpu_dra_driver.pkg import metrics as _metrics
from tpu_dra_driver.pkg import tracing
from tpu_dra_driver.pkg.flock import Flock, FlockOptions
from tpu_dra_driver.plugin.allocatable import (
    AllocatableDevice,
    DeviceType,
    enumerate_allocatable,
)
from tpu_dra_driver.plugin.checkpoint import (
    Checkpoint,
    CheckpointManager,
    ClaimEntry,
    GroupCommitWriter,
    JOURNAL_OP_DEL,
    JOURNAL_OP_PUT,
    JournalCheckpointManager,
    PreparedDevice,
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    backfill_pools,
    fold_journal_into_base,
)
from tpu_dra_driver.plugin.claims import (
    ClaimInfo,
    config_for_result,
    resolve_opaque_configs,
)
from tpu_dra_driver.plugin.allocatable import SEAT_HBM_PERCENT
from tpu_dra_driver.plugin.repartition import RepartitionManager
from tpu_dra_driver.plugin.sharing import MultiProcessManager, TimeSlicingManager
from tpu_dra_driver.plugin.vfio import VfioPciManager
from tpu_dra_driver.tpulib.interface import (
    SharingExhaustedError,
    SubsliceAlreadyExistsError,
    TpuLib,
    TpuLibError,
)
from tpu_dra_driver.tpulib.partition import (
    ParsedChip,
    ParsedShared,
    ParsedSubslice,
    ParsedVfio,
    SubsliceSpec,
    parse_canonical_name,
)

log = logging.getLogger(__name__)

fi.register("plugin.prepare.after_write_ahead",
            "between the PrepareStarted write-ahead fsync and device "
            "preparation (crash = claims written-ahead but no hardware "
            "touched; restart must roll them back)")
fi.register("plugin.prepare.before_commit",
            "between device preparation and the PrepareCompleted commit "
            "fsync (crash = devices live but checkpoint says "
            "PrepareStarted; restart must roll back and re-prepare)")
fi.register("plugin.unprepare.before_write",
            "after device teardown, before the checkpoint write removing "
            "the entries (crash = devices gone but entries persist; "
            "re-unprepare must be idempotent)")


class PermanentError(Exception):
    """Non-retryable prepare failure (bad user input); surfaced to the user
    via a kubelet event instead of being retried (reference
    compute-domain-kubelet-plugin/driver.go:40-62 distinguishes these)."""


@dataclass
class PrepareTiming:
    claim: str
    t_total: float = 0.0
    t_checkpoint: float = 0.0
    t_core: float = 0.0
    t_cdi: float = 0.0
    cached: bool = False


@dataclass
class BatchClaimResult:
    """Per-claim outcome of a group-commit prepare batch.

    ``exception`` carries the original exception object (when any) so
    the single-claim ``prepare()`` wrapper can re-raise it unchanged;
    ``error``/``permanent`` are derived projections for kubelet, so the
    three can never drift apart."""

    devices: List[PreparedDevice] = field(default_factory=list)
    cached: bool = False
    exception: Optional[BaseException] = None

    @property
    def error(self) -> Optional[str]:
        return None if self.exception is None else str(self.exception)

    @property
    def permanent(self) -> bool:
        return isinstance(self.exception, PermanentError)


class DeviceState:
    def __init__(self, lib: TpuLib, gates: fg.FeatureGates,
                 cdi: CdiHandler, state_dir: str):
        self._lib = lib
        self._gates = gates
        self._cdi = cdi
        self._mu = threading.RLock()
        self._cp_lock_path = os.path.join(state_dir, "cp.lock")
        #: dynamic placement has no internal locking (it historically ran
        #: under _mu + cp flock); parallel actuation serializes it here
        self._place_mu = threading.Lock()
        self.journal_mode = gates.enabled(fg.JOURNAL_CHECKPOINT)
        if self.journal_mode:
            # append-only journal + cross-batch group commit: state is
            # authoritative IN MEMORY (single-writer ownership of the
            # state dir), every transition an appended record; the cp
            # flock is held only across recovery — steady-state commits
            # are serialized by the writer thread instead
            self._jcp_mgr = JournalCheckpointManager(state_dir)
            with self._cp_locked():
                self._cp_mem: Checkpoint = self._jcp_mgr.recover()
            self._cp_mgr = self._jcp_mgr.base
            self._restore_claim_specs(self._cp_mem)
            self.journal_writer = GroupCommitWriter(
                self._jcp_mgr, snapshot=self._cp_snapshot)
            self._actuate_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="prepare-actuate")
        else:
            self._cp_mgr = CheckpointManager(state_dir)
            with self._cp_locked():
                # downgrade path: fold any journal left by a journaled
                # run into the base so rewrite-format readers see it all
                fold_journal_into_base(state_dir)
                self._cp_mgr.ensure_exists()
        self._timeslicing = TimeSlicingManager(lib)
        self._multiprocess = MultiProcessManager(lib)
        self.repartition = RepartitionManager(lib, state_dir)
        self.vfio = VfioPciManager(lib)
        self.allocatable: Dict[str, AllocatableDevice] = enumerate_allocatable(lib, gates)
        # bounded: one entry per recent prepare (benchmark/diagnostic data,
        # not an unbounded log for the life of the daemon)
        self.timings: Deque[PrepareTiming] = deque(maxlen=4096)

    # ------------------------------------------------------------------
    # checkpoint plumbing
    # ------------------------------------------------------------------

    def refresh_allocatable(self) -> None:
        """Re-enumerate after hardware-visible changes (vfio driver flips
        swap a chip's personality; reference allocatable.go:238-273)."""
        with self._mu:
            self.allocatable = enumerate_allocatable(self._lib, self._gates)

    def _cp_locked(self):
        return Flock(self._cp_lock_path, FlockOptions(timeout=10.0))

    def _cp_snapshot(self) -> Checkpoint:
        """Point-in-time copy of the in-memory checkpoint (journal mode;
        the group-commit writer compacts against this)."""
        with self._mu:
            return self._cp_mem.deepcopy()

    def _restore_claim_specs(self, cp: Checkpoint) -> None:
        """Journal-mode recovery: the prepare path writes CDI spec files
        WITHOUT a per-file fsync (the body's durability is the fsynced
        journal record carrying the entry), so after a crash a committed
        claim's spec file may be missing or torn. Rewrite any divergent
        spec durably from its checkpointed body before serving."""
        for uid, entry in cp.claims.items():
            if entry.state != PREPARE_COMPLETED or not entry.cdi_spec:
                continue
            if self._cdi.restore_claim_spec(uid, entry.cdi_spec):
                log.info("recovery: restored CDI spec for claim %s from "
                         "its checkpoint entry", uid)

    def get_checkpoint(self) -> Checkpoint:
        if self.journal_mode:
            return self._cp_snapshot()
        with self._cp_locked():
            return self._cp_mgr.read_or_quarantine()

    def close(self) -> None:
        """Stop journal-mode background machinery (writer thread +
        actuation pool). Safe to call repeatedly; a no-op in rewrite
        mode. In-process restarts (drills, rolling upgrades, soak)
        must not strand one writer thread per plugin generation."""
        if not self.journal_mode:
            return
        self.journal_writer.stop()
        self._actuate_pool.shutdown(wait=True, cancel_futures=True)
        self._jcp_mgr.close()

    # ------------------------------------------------------------------
    # Prepare
    # ------------------------------------------------------------------

    def prepare(self, claim: ClaimInfo) -> List[PreparedDevice]:
        """Single-claim prepare: the group-commit path with a batch of
        one. Kept for callers that want the exception contract (raises
        PermanentError / TpuLibError / FlockTimeoutError) rather than
        per-claim results."""
        res = self.prepare_batch([claim])[claim.uid]
        if res.exception is not None:
            raise res.exception
        return res.devices

    def prepare_batch(self, claims: List[ClaimInfo],
                      spans: Optional[Dict[str, object]] = None
                      ) -> Dict[str, BatchClaimResult]:
        """Group-commit prepare for one kubelet batch.

        The whole batch pays ONE cp-lock acquisition, ONE checkpoint
        read, ONE write-ahead fsync (PrepareStarted for every admitted
        claim), then per-claim device preparation with per-claim error
        isolation — a claim failing (even permanently) must not fail or
        roll back its batch peers — and ONE commit fsync. Crash recovery
        is unchanged from the per-claim write-ahead: any entry still
        PrepareStarted on disk (failed peer, or a crash between
        write-ahead and commit) is rolled back by the next prepare
        attempt / startup sweep, exactly as before.

        ``spans`` (optional, from the driver's trace pickup) maps claim
        uid → its ``kubelet.prepare`` span: each claim's device/CDI
        phase spans parent on ITS OWN trace, while the genuinely
        batch-wide fsync spans (read/write-ahead/commit) stay on the
        ambient batch span.

        Batch-wide failures (cp-lock timeout, checkpoint corruption)
        raise; everything per-claim is reported in the result map.
        """
        spans = spans or {}
        out: Dict[str, BatchClaimResult] = {}
        if not claims:
            return out
        t0 = time.perf_counter()
        _metrics.PREPARE_BATCH_CLAIMS.observe(len(claims))
        if self.journal_mode:
            return self._prepare_batch_journal(claims, spans)
        phase = _metrics.PREPARE_BATCH_PHASE_SECONDS.labels
        with self._mu:
            t_lock0 = time.perf_counter()
            with self._cp_locked():
                phase("lock").observe(time.perf_counter() - t_lock0)
                t_read0 = time.perf_counter()
                with tracing.span("prepare.read_checkpoint"):
                    cp = self._cp_mgr.read_or_quarantine()
                t_read = time.perf_counter() - t_read0
                phase("read").observe(t_read)

                to_prepare = self._admit_claims(cp, claims, out, t_read)

                if not to_prepare:
                    return out

                # write-ahead: one fsync covers every admitted claim
                for claim in to_prepare:
                    cp.claims[claim.uid] = ClaimEntry(
                        claim_uid=claim.uid, claim_name=claim.name,
                        namespace=claim.namespace, state=PREPARE_STARTED,
                    )
                t_wa0 = time.perf_counter()
                with tracing.span("prepare.write_ahead",
                                  attributes={"claims": len(to_prepare)}):
                    self._cp_mgr.write(cp)
                phase("write_ahead").observe(
                    time.perf_counter() - t_wa0,
                    exemplar=tracing.exemplar())
                fi.fire("plugin.prepare.after_write_ahead")

                t_prep0 = time.perf_counter()
                for claim in to_prepare:
                    # per-claim phases land in the CLAIM's own trace;
                    # use_span(None) keeps the ambient batch span for
                    # untraced claims
                    with tracing.use_span(spans.get(claim.uid)):
                        out[claim.uid] = self._prepare_one_in_batch(
                            claim, cp, t_read)
                phase("prepare").observe(time.perf_counter() - t_prep0,
                                         exemplar=tracing.exemplar())

                # commit: one fsync finalizes every successful claim.
                # Failed peers keep their PrepareStarted write-ahead
                # entries in this same write — the rollback contract.
                # A batch where NO claim completed has nothing to
                # finalize: cp is byte-identical to the write-ahead, so
                # the commit fsync is skipped (failed entries already
                # persist for rollback).
                if any(out[c.uid].exception is None for c in to_prepare):
                    fi.fire("plugin.prepare.before_commit")
                    t_commit0 = time.perf_counter()
                    with tracing.span("prepare.commit"):
                        self._cp_mgr.write(cp)
                    phase("commit").observe(time.perf_counter() - t_commit0,
                                            exemplar=tracing.exemplar())
        log.debug("prepare batch: %d claim(s) in %.1fms",
                  len(claims), (time.perf_counter() - t0) * 1e3)
        return out

    def _admit_claims(self, cp: Checkpoint, claims: List[ClaimInfo],
                      out: Dict[str, BatchClaimResult],
                      t_read: float) -> List[ClaimInfo]:
        """The batch admission loop (shared by both persistence modes):
        idempotent completed hits, the overlap guard against pre-existing
        owners, and rollback of PrepareStarted leftovers. Called under
        the state lock; fills ``out`` for claims decided here and returns
        the list to actually prepare."""
        to_prepare: List[ClaimInfo] = []
        admitted: Set[str] = set()
        for claim in claims:
            if claim.uid in out or claim.uid in admitted:
                # duplicate UID within one batch: the first
                # occurrence decides (the serial path's second
                # pass would have seen its completed entry)
                continue
            entry = cp.claims.get(claim.uid)
            if entry is not None and entry.state == PREPARE_COMPLETED:
                t_claim0 = time.perf_counter()
                log.debug("prepare %s: already completed (idempotent)",
                          claim.canonical)
                backfill_pools(entry, claim)
                timing = PrepareTiming(claim=claim.canonical,
                                       cached=True,
                                       t_checkpoint=t_read)
                timing.t_total = time.perf_counter() - t_claim0
                self.timings.append(timing)
                out[claim.uid] = BatchClaimResult(
                    devices=entry.prepared_devices, cached=True)
                continue
            try:
                # against PRE-EXISTING owners only; a conflict
                # with a batch peer is decided in the prepare
                # loop below, after the peer's actual outcome
                self._validate_no_overlap(cp, claim)
            except (PermanentError, TpuLibError) as e:
                # TpuLibError = the transient dynamic-placement
                # conflict: still isolated to this claim, but
                # retriable
                log.error("prepare %s failed (%s): %s",
                          claim.canonical, type(e).__name__, e)
                out[claim.uid] = BatchClaimResult(exception=e)
                continue
            if entry is not None and entry.state == PREPARE_STARTED:
                # crashed mid-prepare earlier: roll the partial
                # attempt back
                log.info("prepare %s: rolling back partial previous "
                         "attempt", claim.canonical)
                self._unprepare_devices(entry, best_effort=True)
            admitted.add(claim.uid)
            to_prepare.append(claim)
        return to_prepare

    # ------------------------------------------------------------------
    # journal mode: group-commit prepare pipeline
    # ------------------------------------------------------------------

    def _prepare_batch_journal(self, claims: List[ClaimInfo],
                               spans: Dict[str, object]
                               ) -> Dict[str, BatchClaimResult]:
        """The journaled prepare pipeline: admission under the state
        lock, write-ahead as appended journal records (one group-commit
        fsync SHARED with every other in-flight batch), parallel device
        actuation through the TpuLib seam, then commit records through
        the same group commit. Crash semantics are identical to the
        rewrite path — PrepareStarted is durable before any device
        mutation, PrepareCompleted only after the CDI spec is on disk —
        but N concurrent batches now pay O(1) fsyncs instead of 2N."""
        out: Dict[str, BatchClaimResult] = {}
        phase = _metrics.PREPARE_BATCH_PHASE_SECONDS.labels
        w = self.journal_writer
        w.batch_begin()
        try:
            with self._mu:
                cp = self._cp_mem
                to_prepare = self._admit_claims(cp, claims, out, 0.0)
                if not to_prepare:
                    return out
                # write-ahead records enqueued UNDER the state lock
                # (journal order must equal memory order); the fsync
                # wait happens after release so concurrent batches
                # coalesce instead of convoying
                for claim in to_prepare:
                    cp.claims[claim.uid] = ClaimEntry(
                        claim_uid=claim.uid, claim_name=claim.name,
                        namespace=claim.namespace, state=PREPARE_STARTED,
                    )
                ticket = w.enqueue(
                    [(JOURNAL_OP_PUT, c.uid, cp.claims[c.uid].to_obj())
                     for c in to_prepare])
            t_wa0 = time.perf_counter()
            with tracing.span("prepare.write_ahead",
                              attributes={"claims": len(to_prepare)}):
                ticket.wait(30.0)
            phase("write_ahead").observe(time.perf_counter() - t_wa0,
                                         exemplar=tracing.exemplar())
            fi.fire("plugin.prepare.after_write_ahead")

            t_prep0 = time.perf_counter()
            self._actuate_claims(to_prepare, cp, spans, out)
            phase("prepare").observe(time.perf_counter() - t_prep0,
                                     exemplar=tracing.exemplar())

            completed = [c for c in to_prepare
                         if out[c.uid].exception is None]
            if completed:
                fi.fire("plugin.prepare.before_commit")
                with self._mu:
                    ticket = w.enqueue(
                        [(JOURNAL_OP_PUT, c.uid, cp.claims[c.uid].to_obj())
                         for c in completed])
                t_c0 = time.perf_counter()
                with tracing.span("prepare.commit"):
                    ticket.wait(30.0)
                phase("commit").observe(time.perf_counter() - t_c0,
                                        exemplar=tracing.exemplar())
        finally:
            w.batch_end()
        return out

    def _actuate_claims(self, to_prepare: List[ClaimInfo], cp: Checkpoint,
                        spans: Dict[str, object],
                        out: Dict[str, BatchClaimResult]) -> None:
        """Fan device actuation out across the batch (journal mode).

        Claims that share a (non-admin) device with an earlier batch
        peer are chained AFTER that peer, preserving the serial-run
        overlap equivalence the rewrite path guarantees; the mutually
        independent chains run in parallel through the TpuLib seam —
        the journal serializes state, so device work no longer needs
        the state lock for the whole batch."""
        chains: List[List[ClaimInfo]] = []
        chain_of: Dict[str, int] = {}   # device name -> chain index
        for claim in to_prepare:
            devs = {r.device for r in claim.results if not r.admin_access}
            idxs = sorted({chain_of[d] for d in devs if d in chain_of})
            if not idxs:
                chains.append([claim])
                idx = len(chains) - 1
            else:
                # this claim bridges several so-far-independent chains:
                # merge them (their devices are disjoint, so relative
                # order between them is immaterial; within each chain,
                # batch order is preserved)
                idx = idxs[0]
                for j in idxs[1:]:
                    chains[idx].extend(chains[j])
                    chains[j] = []
                for d, ci in list(chain_of.items()):
                    if ci in idxs[1:]:
                        chain_of[d] = idx
                chains[idx].append(claim)
            for d in devs:
                chain_of[d] = idx

        def run_chain(chain: List[ClaimInfo]) -> None:
            for claim in chain:
                with tracing.use_span(spans.get(claim.uid)):
                    out[claim.uid] = self._prepare_one_in_batch(
                        claim, cp, 0.0)

        live = [ch for ch in chains if ch]
        if len(live) <= 1:
            for ch in live:
                run_chain(ch)
            return
        futures = [self._actuate_pool.submit(run_chain, ch) for ch in live]
        for f in futures:
            f.result()

    # ------------------------------------------------------------------
    # journal mode: unprepare
    # ------------------------------------------------------------------

    def _unprepare_batch_journal(self, claim_uids: List[str]
                                 ) -> Dict[str, Optional[BaseException]]:
        out: Dict[str, Optional[BaseException]] = {}
        w = self.journal_writer
        w.batch_begin()
        ops: List[tuple] = []
        try:
            with self._mu:
                cp = self._cp_mem
                for uid in claim_uids:
                    entry = cp.claims.get(uid)
                    if entry is None:
                        log.debug("unprepare %s: no checkpoint entry "
                                  "(idempotent)", uid)
                        out[uid] = None
                        continue
                    try:
                        self._unprepare_devices(entry, best_effort=False)
                        self._cdi.delete_claim_spec(uid)
                    except Exception as e:  # chaos-ok: kept for retry
                        log.exception("unprepare %s failed", uid)
                        out[uid] = e
                        continue
                    del cp.claims[uid]
                    ops.append((JOURNAL_OP_DEL, uid, None))
                    out[uid] = None
                    log.info("unprepare %s: done", uid)
                if ops:
                    fi.fire("plugin.unprepare.before_write")
                    ticket = w.enqueue(ops)
            if ops:
                ticket.wait(30.0)
        finally:
            w.batch_end()
        return out

    def _prepare_one_in_batch(self, claim: ClaimInfo, cp: Checkpoint,
                              t_read: float) -> BatchClaimResult:
        """Device preparation + CDI write for one claim of a batch, with
        its errors isolated to that claim. On success the claim's entry
        in ``cp`` flips to PrepareCompleted (persisted by the batch
        commit); on failure it stays PrepareStarted for rollback.

        ``t_total`` is this claim's OWN wall time (the shared
        lock/read/fsync costs are amortized batch-wide and reported by
        the dra_prepare_batch_phase_seconds histogram instead), so the
        breadcrumb stays per-claim honest at any batch size."""
        t_claim0 = time.perf_counter()
        timing = PrepareTiming(claim=claim.canonical, t_checkpoint=t_read)
        try:
            # serial-run equivalence for intra-batch overlap: ``cp``
            # holds PrepareCompleted entries for batch peers that
            # ACTUALLY succeeded, so a claim loses a shared device to
            # an earlier peer only if that peer completed — exactly the
            # error (and message) a serial run produces; if the peer
            # failed, this claim proceeds, just as it would serially.
            # (under _mu: journal-mode actuation threads share ``cp``
            # with concurrent batches' admission; _mu is reentrant for
            # the rewrite path, which already holds it)
            with self._mu:
                self._validate_no_overlap(cp, claim)
            t_core0 = time.perf_counter()
            with tracing.span("prepare.devices",
                              attributes={"claim": claim.canonical}):
                prepared, cdi_devices, extra_common = \
                    self._prepare_devices(claim, cp)
            timing.t_core = time.perf_counter() - t_core0

            t_cdi0 = time.perf_counter()
            with tracing.span("prepare.cdi",
                              attributes={"claim": claim.canonical}):
                spec_body, qualified = self._cdi.render_claim_spec(
                    claim.uid, cdi_devices, extra_common=extra_common)
                # journal mode: the rendered body rides the fsynced
                # journal record (and is restored from it on recovery),
                # so the spec FILE skips its per-claim fsync — the
                # coalesced journal fsync is the prepare path's only one
                self._cdi.write_claim_spec_body(
                    claim.uid, spec_body, durable=not self.journal_mode)
            timing.t_cdi = time.perf_counter() - t_cdi0
        except PermanentError as e:
            log.error("prepare %s failed permanently: %s", claim.canonical, e)
            return BatchClaimResult(exception=e)
        except Exception as e:  # chaos-ok: isolated to this claim's result
            log.exception("prepare %s failed", claim.canonical)
            return BatchClaimResult(exception=e)
        for dev, qname in zip(prepared, qualified):
            dev.cdi_device_ids = [qname]
        with self._mu:
            cp.claims[claim.uid] = ClaimEntry(
                claim_uid=claim.uid, claim_name=claim.name,
                namespace=claim.namespace, state=PREPARE_COMPLETED,
                prepared_devices=prepared,
                cdi_spec=spec_body if self.journal_mode else "",
            )
        timing.t_total = time.perf_counter() - t_claim0
        self.timings.append(timing)
        log.info("prepare %s: %d device(s) in %.1fms (core=%.1fms cdi=%.1fms)",
                 claim.canonical, len(prepared), timing.t_total * 1e3,
                 timing.t_core * 1e3, timing.t_cdi * 1e3)
        return BatchClaimResult(devices=prepared)

    def _validate_no_overlap(self, cp: Checkpoint, claim: ClaimInfo) -> None:
        owners = cp.prepared_device_owners()
        for r in claim.results:
            if r.admin_access:
                continue  # admin-access claims may observe busy devices
            owner = owners.get(r.device)
            if owner is not None and owner != claim.uid:
                entry = cp.claims.get(owner)
                dynamically_placed = entry is not None and any(
                    d.canonical_name == r.device and d.source_device
                    for d in entry.prepared_devices)
                if dynamically_placed:
                    # the busy device is a DYNAMIC placement (a PROFILE
                    # claim journaled this -ss- name; the pre-cut device
                    # was admitted during the republish-lag window):
                    # transient — the placement will be reclaimed or the
                    # claim re-placed, so kubelet may retry
                    raise TpuLibError(
                        f"device {r.device} is occupied by claim "
                        f"{owner}'s dynamic placement (transient: "
                        f"retry after reclaim or re-placement)"
                    )
                raise PermanentError(
                    f"device {r.device} is already prepared for claim {owner}"
                )

    # ------------------------------------------------------------------

    def _prepare_devices(self, claim: ClaimInfo, cp: Checkpoint):
        try:
            configs = resolve_opaque_configs(claim, STRICT_DECODER)
        except DecodeError as e:
            raise PermanentError(f"bad opaque config: {e}") from e
        except ValidationError as e:
            # normalize()/validate() failures are the same class of bad
            # user input as a decode error: retrying without a config
            # change cannot succeed (previously these surfaced as
            # transient errors and kubelet retried them forever)
            raise PermanentError(str(e)) from e

        if not claim.results:
            raise PermanentError(
                f"claim {claim.canonical} has no allocation results for this driver"
            )

        prepared: List[PreparedDevice] = []
        cdi_devices: List[CdiDevice] = []
        extra_common = ContainerEdits()
        visible_chips: List[int] = []
        sharing_applied: Set[str] = set()

        for result in claim.results:
            dev = self.allocatable.get(result.device)
            if dev is None:
                raise PermanentError(
                    f"allocated device {result.device!r} is not in this "
                    f"node's allocatable inventory"
                )
            rc = config_for_result(configs, result)
            cfg = rc.config if rc else None
            self._check_config_type(dev, cfg, result.device)

            if dev.type == DeviceType.CHIP:
                pd, cd = self._prepare_chip(claim, result.request, dev)
                if dev.chip.index not in visible_chips:
                    visible_chips.append(dev.chip.index)
            elif dev.type == DeviceType.SUBSLICE:
                pd, cd = self._prepare_subslice(claim, result.request, dev)
            elif dev.type == DeviceType.PROFILE:
                pd, cd = self._prepare_profile(claim, result.request, dev,
                                               cp)
            elif dev.type == DeviceType.SHARED:
                pd, cd = self._prepare_shared(claim, result.request, dev)
            else:
                pd, cd = self._prepare_vfio(claim, result.request, dev)
            pd.pool = result.pool
            prepared.append(pd)
            cdi_devices.append(cd)

            # sharing config applies once per underlying chip
            if cfg is not None and dev.chip.uuid not in sharing_applied:
                edits = self._apply_sharing(claim, dev, cfg)
                if edits is not None:
                    extra_common = extra_common.merge(edits)
                    sharing_applied.add(dev.chip.uuid)

        if visible_chips:
            chips_csv = ",".join(str(i) for i in sorted(visible_chips))
            extra_common = extra_common.merge(ContainerEdits(env={
                "TPU_VISIBLE_CHIPS": chips_csv,
                # legacy libtpu spelling
                "TPU_VISIBLE_DEVICES": chips_csv,
            }))
        return prepared, cdi_devices, extra_common

    def _check_config_type(self, dev: AllocatableDevice, cfg, name: str) -> None:
        if cfg is None:
            return
        if dev.type == DeviceType.SHARED:
            # a seat's budget is a fixed published contract (capacity +
            # counters were rendered from it); a per-claim config cannot
            # renegotiate it
            raise PermanentError(
                f"shared-seat device {name} accepts no per-claim config "
                f"(seat budgets are fixed at publish time)"
            )
        ok = (
            (dev.type == DeviceType.CHIP and isinstance(cfg, TpuConfig))
            or (dev.type in (DeviceType.SUBSLICE, DeviceType.PROFILE)
                and isinstance(cfg, SubsliceConfig))
            or (dev.type == DeviceType.VFIO and isinstance(cfg, VfioTpuConfig))
        )
        if not ok:
            raise PermanentError(
                f"config type {type(cfg).__name__} cannot apply to "
                f"{dev.type.value} device {name}"
            )

    def _apply_sharing(self, claim: ClaimInfo, dev: AllocatableDevice,
                       cfg) -> Optional[ContainerEdits]:
        sharing = getattr(cfg, "sharing", None)
        if sharing is None:
            return None
        if sharing.strategy == "TimeSlicing":
            if not self._gates.enabled(fg.TIME_SLICING_SETTINGS):
                raise PermanentError(
                    "TimeSlicing sharing requested but the "
                    "TimeSlicingSettings feature gate is disabled"
                )
            return self._timeslicing.apply([dev.chip.uuid], sharing.time_slicing)
        if not self._gates.enabled(fg.MULTI_PROCESS_SHARING):
            raise PermanentError(
                "MultiProcess sharing requested but the "
                "MultiProcessSharing feature gate is disabled"
            )
        try:
            return self._multiprocess.apply(
                [dev.chip.uuid], sharing.multi_process, owner=claim.uid)
        except SharingExhaustedError as e:
            # over-subscribed limits / foreign share: retrying without a
            # config change cannot succeed
            raise PermanentError(str(e)) from e

    def _prepare_chip(self, claim: ClaimInfo, request: str,
                      dev: AllocatableDevice):
        edits = ContainerEdits(device_nodes=[{"path": dev.chip.devfs_path}])
        name = self._cdi.claim_device_name(claim.uid, dev.canonical_name)
        pd = PreparedDevice(
            canonical_name=dev.canonical_name, request=request,
            device_type="chip", live_uuid=dev.chip.uuid,
            devfs_path=dev.chip.devfs_path,
        )
        return pd, CdiDevice(name=name, edits=edits)

    def _prepare_subslice(self, claim: ClaimInfo, request: str,
                          dev: AllocatableDevice):
        if not self._gates.enabled(fg.DYNAMIC_SUBSLICE):
            raise PermanentError(
                "sub-slice device allocated but DynamicSubslice gate is off"
            )
        assert dev.profile is not None
        spec = SubsliceSpec(dev.chip.index, dev.chip.uuid, dev.profile,
                            dev.placement_start)
        with tracing.span("prepare.subslice",
                          attributes={"profile": dev.profile.id,
                                      "chip": dev.chip.index}):
            try:
                live = self._lib.create_subslice(spec)
            except SubsliceAlreadyExistsError:
                # Leftover from an earlier crashed attempt of *this* claim
                # (other owners were excluded by the overlap guard):
                # recreate for a clean slate.
                self._lib.destroy_subslice(spec.tuple)
                live = self._lib.create_subslice(spec)
        edits = ContainerEdits(
            device_nodes=[{"path": live.devfs_path}],
            env={
                "TPU_SUBSLICE_PROFILE": dev.profile.id,
                "TPU_SUBSLICE_START_CORE": str(dev.placement_start),
            },
        )
        name = self._cdi.claim_device_name(claim.uid, dev.canonical_name)
        pd = PreparedDevice(
            canonical_name=dev.canonical_name, request=request,
            device_type="subslice", live_uuid=live.uuid,
            devfs_path=live.devfs_path,
        )
        return pd, CdiDevice(name=name, edits=edits)

    def _prepare_profile(self, claim: ClaimInfo, request: str,
                         dev: AllocatableDevice, cp: Checkpoint):
        """Create-on-prepare for a *creatable profile slot*: the claim
        allocated a shape, this node picks the placement. The checkpoint
        records the CONCRETE placed ``-ss-`` canonical name (the
        recovery contract needs exactly one parser) with the allocated
        slot name in ``source_device``."""
        if not self._gates.enabled(fg.DYNAMIC_REPARTITION):
            raise PermanentError(
                "profile-slot device allocated but DynamicRepartition "
                "gate is off"
            )
        assert dev.profile is not None
        with tracing.span("prepare.subslice",
                          attributes={"profile": dev.profile.id,
                                      "chip": dev.chip.index,
                                      "dynamic": True}):
            # placement reads checkpoint occupancy and has no locking of
            # its own; parallel actuation serializes it explicitly
            with self._place_mu, self._mu:
                spec, live = self.repartition.place(dev.chip, dev.profile,
                                                    cp)
        placed_name = spec.canonical_name()
        edits = ContainerEdits(
            device_nodes=[{"path": live.devfs_path}],
            env={
                "TPU_SUBSLICE_PROFILE": dev.profile.id,
                "TPU_SUBSLICE_START_CORE": str(spec.placement_start),
            },
        )
        name = self._cdi.claim_device_name(claim.uid, placed_name)
        pd = PreparedDevice(
            canonical_name=placed_name, request=request,
            device_type="subslice", live_uuid=live.uuid,
            devfs_path=live.devfs_path, source_device=dev.canonical_name,
        )
        return pd, CdiDevice(name=name, edits=edits)

    def _prepare_shared(self, claim: ClaimInfo, request: str,
                        dev: AllocatableDevice):
        """Attach one multi-process client seat (claim-per-request
        serving): the chip's device node plus the bounded-client env the
        runtime allocator reads."""
        if not self._gates.enabled(fg.SHARED_CHIP_SERVING):
            raise PermanentError(
                "shared-seat device allocated but SharedChipServing "
                "gate is off"
            )
        try:
            edits = self._multiprocess.attach_seat(
                dev.chip.uuid, dev.slot, owner=claim.uid,
                hbm_limit_percent=SEAT_HBM_PERCENT)
        except SharingExhaustedError as e:
            raise PermanentError(str(e)) from e
        # seat density changes the chip's advertisable personalities
        # (whole-chip hidden while seats live) — trigger the advertise step
        self.repartition.mark_dirty()
        edits = edits.merge(ContainerEdits(
            device_nodes=[{"path": dev.chip.devfs_path}]))
        name = self._cdi.claim_device_name(claim.uid, dev.canonical_name)
        pd = PreparedDevice(
            canonical_name=dev.canonical_name, request=request,
            device_type="shared", live_uuid=dev.chip.uuid,
            devfs_path=dev.chip.devfs_path,
        )
        return pd, CdiDevice(name=name, edits=edits)

    def _prepare_vfio(self, claim: ClaimInfo, request: str,
                      dev: AllocatableDevice):
        if not self._gates.enabled(fg.PASSTHROUGH_SUPPORT):
            raise PermanentError(
                "vfio device allocated but PassthroughSupport gate is off"
            )
        # vfio driver flips mutate shared manager state; serialize them
        # (rewrite mode already holds the reentrant _mu)
        with self._mu:
            group = self.vfio.configure(dev.chip.pci_address)
            edits = self.vfio.container_edits(group)
        name = self._cdi.claim_device_name(claim.uid, dev.canonical_name)
        pd = PreparedDevice(
            canonical_name=dev.canonical_name, request=request,
            device_type="vfio", live_uuid=dev.chip.uuid, devfs_path=group,
        )
        return pd, CdiDevice(name=name, edits=edits)

    # ------------------------------------------------------------------
    # Unprepare
    # ------------------------------------------------------------------

    def unprepare(self, claim_uid: str) -> None:
        """Single-claim unprepare: the batch path with a batch of one,
        re-raising that claim's teardown error (if any)."""
        exc = self.unprepare_batch([claim_uid])[claim_uid]
        if exc is not None:
            raise exc

    def unprepare_batch(self, claim_uids: Iterable[str]
                        ) -> Dict[str, Optional[BaseException]]:
        """Batched unprepare mirroring the prepare side: one cp-lock
        acquisition and one checkpoint read for the whole kubelet batch,
        per-UID teardown with per-UID error isolation, and a single
        fsync-bearing checkpoint write removing every torn-down entry.
        Returns uid -> None on success (or idempotent no-op) / the
        original exception on failure (that UID's entry is kept so a
        retry can finish the teardown)."""
        out: Dict[str, Optional[BaseException]] = {}
        claim_uids = list(claim_uids)
        if not claim_uids:
            return out
        _metrics.UNPREPARE_BATCH_CLAIMS.observe(len(claim_uids))
        if self.journal_mode:
            return self._unprepare_batch_journal(claim_uids)
        with self._mu, self._cp_locked():
            cp = self._cp_mgr.read_or_quarantine()
            dirty = False
            for uid in claim_uids:
                entry = cp.claims.get(uid)
                if entry is None:
                    log.debug("unprepare %s: no checkpoint entry (idempotent)",
                              uid)
                    out[uid] = None
                    continue
                try:
                    self._unprepare_devices(entry, best_effort=False)
                    self._cdi.delete_claim_spec(uid)
                except Exception as e:  # chaos-ok: kept for retry, error surfaced
                    log.exception("unprepare %s failed", uid)
                    out[uid] = e
                    continue
                del cp.claims[uid]
                dirty = True
                out[uid] = None
                log.info("unprepare %s: done", uid)
            if dirty:
                fi.fire("plugin.unprepare.before_write")
                self._cp_mgr.write(cp)
        return out

    def _unprepare_devices(self, entry: ClaimEntry, best_effort: bool) -> None:
        """Tear down by canonical name alone — works even when the entry
        was written by a process that died before recording live handles.
        (A PrepareStarted entry has no recorded devices; its partial
        hardware state is recovered by the idempotent per-type prepare
        paths, the startup destroy_unknown_subslices sweep, and the seat
        sweep below.)"""
        if not entry.prepared_devices:
            # write-ahead-only entry: a crashed/failed attempt may have
            # attached a client seat before dying (seats precede the CDI
            # write and carry the claim uid in the device-library ledger)
            # — detach whatever this claim still holds so rollback cannot
            # leak a seat that would poison its index forever
            for chip in self._lib.enumerate_chips():
                if self._lib.list_multiprocess_seats(chip.uuid):
                    self._multiprocess.detach_seat(chip.uuid,
                                                   owner=entry.claim_uid)
                    self.repartition.mark_dirty()
            return
        for dev in entry.prepared_devices:
            parsed = parse_canonical_name(dev.canonical_name)
            try:
                if isinstance(parsed, ParsedSubslice):
                    # idempotent reclaim: an already-destroyed partition
                    # (crashed teardown, retried unprepare) is a clean
                    # no-op inside the repartition state machine
                    self.repartition.reclaim(parsed.tuple)
                    self._reset_chip_sharing(parsed.tuple.parent_index)
                elif isinstance(parsed, ParsedShared):
                    chip = self._chip_by_index(parsed.parent_index)
                    if chip is not None:
                        self._multiprocess.detach_seat(
                            chip.uuid, owner=entry.claim_uid)
                        self.repartition.mark_dirty()
                elif isinstance(parsed, ParsedVfio):
                    chip = self._chip_by_index(parsed.index)
                    if chip is not None:
                        self.vfio.unconfigure(chip.pci_address)
                elif isinstance(parsed, ParsedChip):
                    self._reset_chip_sharing(parsed.index)
            except TpuLibError:
                if not best_effort:
                    raise
                log.warning("best-effort unprepare: failed tearing down %s",
                            dev.canonical_name, exc_info=True)

    def _reset_chip_sharing(self, chip_index: int) -> None:
        """Restore default scheduling (exclusive mode, default time-slice)
        so one claim's sharing config cannot leak into the next claim on
        the same chip (the reference's SetComputeMode-DEFAULT analog)."""
        chip = self._chip_by_index(chip_index)
        if chip is None:
            return
        if self._lib.list_multiprocess_seats(chip.uuid):
            # seat claims own the chip's sharing state (a partition and
            # seats can coexist on distinct cores): flipping the chip
            # back to exclusive here would cut live seat clients off
            return
        self._multiprocess.release([chip.uuid])
        self._timeslicing.reset([chip.uuid])

    def _chip_by_index(self, index: int):
        for dev in self.allocatable.values():
            if dev.chip.index == index:
                return dev.chip
        return None

    # ------------------------------------------------------------------
    # Crash recovery
    # ------------------------------------------------------------------

    def destroy_unknown_subslices(self) -> List[str]:
        """Startup sweep: reconcile live partitions (re-derived from
        canonical names) against checkpoint intent — committed claims'
        partitions adopted, orphans and half-created placements torn
        down, idempotent on re-crash (reference device_state.go:287-373
        DestroyUnknownMIGDevices; the state machine lives in
        plugin/repartition.py). Client SEATS get the same verdicting:
        a seat whose owning claim the checkpoint no longer knows is
        detached, and the density gauge re-seeds from hardware truth
        (seats persist across plugin restarts, the in-process gauge
        does not)."""
        if self.journal_mode:
            with self._mu:
                destroyed = self.repartition.reconcile(self._cp_mem)
                self._reconcile_seats(self._cp_mem)
                return destroyed
        with self._mu, self._cp_locked():
            cp = self._cp_mgr.read_or_quarantine()
            destroyed = self.repartition.reconcile(cp)
            self._reconcile_seats(cp)
            return destroyed

    def _reconcile_seats(self, cp: Checkpoint) -> None:
        known = set(cp.claims)
        total = 0
        for chip in self._lib.enumerate_chips():
            seats = self._lib.list_multiprocess_seats(chip.uuid)
            orphans = [s for s in seats.values() if s.owner not in known]
            for share in orphans:
                log.warning("reconcile: detaching orphan seat %d on chip "
                            "%d (claim %s unknown to the checkpoint)",
                            share.seat, chip.index, share.owner)
                self._lib.detach_multiprocess_seat(chip.uuid,
                                                   owner=share.owner)
                _metrics.SUBSLICE_REPARTITIONS.labels("rollback",
                                                      "ok").inc()
                self.repartition.mark_dirty()
            remaining = (self._lib.list_multiprocess_seats(chip.uuid)
                         if seats else {})
            if orphans and not remaining:
                self._lib.set_exclusive_mode(chip.uuid, True)
            total += len(remaining)
        _metrics.SHARED_CHIP_CLIENTS.set(total)
