"""Versioned, checksummed claim checkpoints with V1/V2 dual-write.

Reference analog: cmd/gpu-kubelet-plugin/{checkpoint.go:26-138,
checkpointv.go:25-98} — a kubelet-checkpointmanager JSON checkpoint with
checksums, written in both a legacy V1 and current V2 layout so upgrades
and *downgrades* both find a readable file (exercised by the reference's
up/downgrade bats tests).

Layout here: one JSON file ``checkpoint.json`` containing both versions::

    {
      "v1": {"claims": {...}},          # legacy: flat prepared-devices list
      "v2": {"claims": {...}},          # current: adds per-claim state machine
      "checksums": {"v1": <crc32>, "v2": <crc32>}
    }

Readers prefer V2 and fall back to V1 (nonstrict: unknown fields in a
newer writer's V2 are ignored on the V1 path). Writes are atomic
(tmp+rename+fsync). Checksum mismatch → checkpoint corruption error, the
caller treats the file as absent-but-alarming (it refuses to guess).
"""

from __future__ import annotations

import copy
import json
import logging
import os
import threading
import time as _time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg import metrics as _metrics

log = logging.getLogger(__name__)

fi.register("checkpoint.read",
            "raw checkpoint file contents on read (corrupt=CRC/JSON "
            "damage, fail=unreadable file)")
fi.register("checkpoint.write",
            "checkpoint serialization before the tmp file is written "
            "(fail with OSError(ENOSPC) models a full disk)")
fi.register("checkpoint.fsync",
            "the fsync of the checkpoint tmp file (fail=ENOSPC at "
            "flush time)")
fi.register("checkpoint.write.torn",
            "between the fsync'd tmp file and the atomic rename "
            "(crash here = a torn write: tmp left behind, the live "
            "checkpoint must stay intact)")
fi.register("journal.append",
            "encoded journal record lines just before the append write "
            "(corrupt=torn/mangled tail, fail=ENOSPC on append, "
            "crash=die before the records become durable — the "
            "committer never acked, so recovery owes it nothing)")
fi.register("journal.compact",
            "between the compacted base landing (atomic rename + dir "
            "fsync) and the journal truncate (crash here = new base "
            "generation with a stale-generation journal; replay must "
            "skip every stale record)")

# Claim prepare states (reference device_state.go:231-283)
PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"


class CheckpointCorruptionError(RuntimeError):
    pass


@dataclass
class PreparedDevice:
    """One prepared device recorded in the checkpoint.

    ``canonical_name`` alone must be enough to recover teardown identity
    after a crash (the MigSpecTuple-from-name contract, SURVEY.md §2.3).
    """

    canonical_name: str
    request: str                     # DRA request name this satisfied
    cdi_device_ids: List[str] = field(default_factory=list)
    device_type: str = "chip"        # chip | subslice | shared | vfio |
                                     # channel | daemon
    live_uuid: str = ""              # live sub-slice uuid (informational)
    devfs_path: str = ""
    pool: str = ""                   # allocation result's pool, echoed to
                                     # kubelet (reference device_state.go:738)
    #: the ALLOCATED device name when it differs from the canonical
    #: identity actually created — a dynamic PROFILE claim allocates
    #: ``tpu-i-prof-<id>-<k>`` but the checkpoint journals the placed
    #: ``tpu-i-ss-<id>-<start>`` partition (the one parser recovery
    #: needs); this field preserves the allocation-side name for
    #: kubelet echo and diagnostics. "" = same as canonical_name.
    source_device: str = ""

    def to_obj(self) -> Dict:
        out = {
            "canonicalName": self.canonical_name,
            "request": self.request,
            "cdiDeviceIDs": list(self.cdi_device_ids),
            "deviceType": self.device_type,
            "liveUUID": self.live_uuid,
            "devfsPath": self.devfs_path,
            "pool": self.pool,
        }
        if self.source_device:
            # written only when set: checkpoints without dynamic claims
            # stay byte-identical to the previous writer's layout (and a
            # downgraded nonstrict reader simply ignores the key)
            out["sourceDevice"] = self.source_device
        return out

    @staticmethod
    def from_obj(d: Dict) -> "PreparedDevice":
        return PreparedDevice(
            canonical_name=d.get("canonicalName", ""),
            request=d.get("request", ""),
            cdi_device_ids=list(d.get("cdiDeviceIDs") or []),
            device_type=d.get("deviceType", "chip"),
            live_uuid=d.get("liveUUID", ""),
            devfs_path=d.get("devfsPath", ""),
            pool=d.get("pool", ""),
            source_device=d.get("sourceDevice", ""),
        )


def backfill_pools(entry: "ClaimEntry", claim) -> None:
    """Fill empty ``pool`` on checkpointed devices from the live claim's
    allocation results. Checkpoints written before the pool field existed
    replay with pool="" on the idempotent re-prepare path, and kubelet
    matches prepared devices by (pool, device) — so upgrades must heal
    in-place (reference device_state.go:738 always echoes result.Pool)."""
    pools = {r.device: r.pool for r in claim.results}
    for pd in entry.prepared_devices:
        if not pd.pool:
            pd.pool = pools.get(pd.canonical_name, "")


@dataclass
class ClaimEntry:
    claim_uid: str
    claim_name: str = ""
    namespace: str = ""
    state: str = PREPARE_STARTED
    prepared_devices: List[PreparedDevice] = field(default_factory=list)
    #: journal mode only: the rendered CDI claim-spec body rides the
    #: fsynced journal record, so the spec FILE can be written without
    #: its own fsync and restored from here on recovery (empty = the
    #: spec file carries its own durability, the rewrite-mode contract)
    cdi_spec: str = ""

    def to_obj(self) -> Dict:
        obj = {
            "claimUID": self.claim_uid,
            "claimName": self.claim_name,
            "namespace": self.namespace,
            "state": self.state,
            "preparedDevices": [d.to_obj() for d in self.prepared_devices],
        }
        if self.cdi_spec:
            obj["cdiSpec"] = self.cdi_spec
        return obj

    @staticmethod
    def from_obj(d: Dict) -> "ClaimEntry":
        return ClaimEntry(
            claim_uid=d.get("claimUID", ""),
            claim_name=d.get("claimName", ""),
            namespace=d.get("namespace", ""),
            state=d.get("state", PREPARE_STARTED),
            prepared_devices=[PreparedDevice.from_obj(x)
                              for x in d.get("preparedDevices") or []],
            cdi_spec=d.get("cdiSpec", ""),
        )


@dataclass
class Checkpoint:
    claims: Dict[str, ClaimEntry] = field(default_factory=dict)  # by claim UID

    def deepcopy(self) -> "Checkpoint":
        return Checkpoint(claims={k: copy.deepcopy(v) for k, v in self.claims.items()})

    # -- queries used by the overlap guard ---------------------------------

    def prepared_device_owners(self) -> Dict[str, str]:
        """canonical device name -> owning claim UID, for claims in
        PrepareCompleted (the overlap guard, device_state.go:1116-1154)."""
        out: Dict[str, str] = {}
        for uid, entry in self.claims.items():
            if entry.state != PREPARE_COMPLETED:
                continue
            for dev in entry.prepared_devices:
                out[dev.canonical_name] = uid
        return out


def _canonical(payload) -> str:
    """The checksum-canonical serialization of a version payload.

    This exact form (sort_keys, default separators) is a compatibility
    contract: every reader ever shipped — including downgraded ones —
    verifies a version by re-serializing the parsed payload this way and
    crc32'ing it. Fully compact separators would shrink the file a bit
    further but would invalidate every stored checksum for old readers
    (and vice versa), so the payload bytes stay canonical; the byte win
    comes from writing each payload flat exactly once instead of
    pretty-printing the whole envelope with indent=1."""
    return json.dumps(payload, sort_keys=True)


def _crc(payload) -> int:
    return zlib.crc32(_canonical(payload).encode())


class CheckpointManager:
    """Owns the checkpoint file. Callers serialize via the cp flock held by
    DeviceState; this class only does (de)serialization + atomicity."""

    FILENAME = "checkpoint.json"

    def __init__(self, state_dir: str):
        self._state_dir = state_dir
        self._path = os.path.join(state_dir, self.FILENAME)
        os.makedirs(state_dir, exist_ok=True)
        #: journal generation recorded in the last file this manager read
        #: or wrote (0 = no journal field: a pure rewrite-format file).
        #: The journal manager layers on this to pair base and journal.
        self.last_journal_gen = 0

    @property
    def path(self) -> str:
        return self._path

    def ensure_exists(self) -> None:
        if not os.path.exists(self._path):
            self.write(Checkpoint())

    def read(self) -> Checkpoint:
        try:
            with open(self._path) as f:
                text = f.read()
        except FileNotFoundError:
            self.last_journal_gen = 0
            return Checkpoint()
        text = fi.fire("checkpoint.read", payload=text)
        try:
            raw = json.loads(text)
        except json.JSONDecodeError as e:
            raise CheckpointCorruptionError(f"{self._path}: invalid JSON: {e}") from e
        self.last_journal_gen = _journal_gen_of(raw)
        checksums = raw.get("checksums") or {}
        for version in ("v2", "v1"):
            payload = raw.get(version)
            if payload is None:
                continue
            if _crc(payload) != checksums.get(version):
                raise CheckpointCorruptionError(
                    f"{self._path}: {version} checksum mismatch"
                )
            claims = self._claims_from_payload(payload, version)
            return Checkpoint(claims=claims)
        return Checkpoint()

    @staticmethod
    def _claims_from_payload(payload: Dict, version: str) -> Dict[str, ClaimEntry]:
        claims: Dict[str, ClaimEntry] = {}
        for uid, e in (payload.get("claims") or {}).items():
            entry = ClaimEntry.from_obj(e)
            if version == "v1" and "state" not in e:
                # legacy layout records only completed claims
                entry.state = PREPARE_COMPLETED
            claims[uid] = entry
        return claims

    # -- corruption recovery (the no-crash-loop contract) -------------------

    def read_or_quarantine(self) -> Checkpoint:
        """Read, but never crash-loop on a corrupt file: quarantine it to
        ``<path>.corrupt-<n>``, log loudly, count it in
        ``dra_checkpoint_quarantined_total``, and continue from the best
        salvageable state — a version whose checksum still verifies
        (readers prefer v2; a damaged v2 falls back to an intact legacy
        v1, which holds every *completed* claim) — or empty when nothing
        verifies. The salvaged state is immediately re-written so the
        next reader sees a healthy file."""
        try:
            return self.read()
        except CheckpointCorruptionError as e:
            salvaged = self._salvage()
            # Quarantine is a COPY: the corrupt original must stay at the
            # live path until the salvaged rewrite's atomic replace lands —
            # renaming it away first would leave NO checkpoint at all if
            # the rewrite fails (ENOSPC is one of the very faults drilled
            # here) or the process dies in the window, silently forgetting
            # every prepared claim on the next (empty) read.
            qpath = self._quarantine_copy()
            _metrics.CHECKPOINT_QUARANTINED.inc()
            log.error(
                "CHECKPOINT CORRUPT: %s — quarantined to %s; continuing "
                "from %s state (prepared-claim history may be incomplete; "
                "the cleanup sweep and idempotent re-prepare will "
                "reconverge)", e, qpath,
                f"salvaged {len(salvaged.claims)}-claim" if salvaged is not None
                else "empty")
            cp = salvaged if salvaged is not None else Checkpoint()
            # preserve the journal pairing on the salvaged rewrite: losing
            # the generation here would orphan (or worse, mis-apply) every
            # record in a live journal paired with this base
            self.write(cp, journal_gen=self.last_journal_gen)
            return cp

    def _salvage(self) -> Optional[Checkpoint]:
        """Best-effort recovery of any version whose checksum still
        verifies (v2 preferred). None when the JSON itself is broken or
        no version survives."""
        self.last_journal_gen = 0
        try:
            with open(self._path) as f:
                raw = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        self.last_journal_gen = _journal_gen_of(raw)
        checksums = raw.get("checksums") or {}
        for version in ("v2", "v1"):
            payload = raw.get(version)
            if payload is None or _crc(payload) != checksums.get(version):
                continue
            return Checkpoint(
                claims=self._claims_from_payload(payload, version))
        return None

    def _quarantine_copy(self) -> str:
        """Preserve the corrupt bytes for postmortem WITHOUT touching the
        live path (best-effort: on a full disk the copy may fail, which
        must not block recovery)."""
        import shutil
        n = 1
        while os.path.exists(f"{self._path}.corrupt-{n}"):
            n += 1
        qpath = f"{self._path}.corrupt-{n}"
        try:
            shutil.copyfile(self._path, qpath)
        except OSError:
            log.warning("could not preserve corrupt checkpoint at %s",
                        qpath, exc_info=True)
            return "<copy failed>"
        return qpath

    def write(self, cp: Checkpoint, journal_gen: Optional[int] = None) -> None:
        v2 = {"claims": {uid: e.to_obj() for uid, e in cp.claims.items()}}
        # V1 (legacy layout): no state machine — only *completed* claims
        # with their device names, the shape a pre-state-machine downgrade
        # reader expects (in-flight PrepareStarted entries are deliberately
        # absent: the legacy reader would have no rollback logic for them).
        v1 = {
            "claims": {
                uid: {
                    "claimUID": e.claim_uid,
                    "claimName": e.claim_name,
                    "namespace": e.namespace,
                    "preparedDevices": [d.to_obj() for d in e.prepared_devices],
                }
                for uid, e in cp.claims.items()
                if e.state == PREPARE_COMPLETED
            }
        }
        # Serialize each version payload exactly ONCE: the same bytes
        # are checksummed and spliced verbatim into the envelope (the
        # old path serialized every payload twice — once in _crc, once
        # inside json.dump — and pretty-printed with indent=1, paying
        # ~40% more bytes per fsync). The envelope keeps a readable
        # top level: one line per section.
        v1_s = _canonical(v1)
        v2_s = _canonical(v2)
        checksums = json.dumps(
            {"v1": zlib.crc32(v1_s.encode()), "v2": zlib.crc32(v2_s.encode())},
            separators=(",", ":"))
        # the journal line sits OUTSIDE the per-version checksums (old
        # nonstrict readers ignore unknown top-level keys, so a downgrade
        # still reads v1/v2); a mangled gen at worst orphans journal
        # records, which replay treats as stale — never mis-applies them
        journal_line = (f'"journal": {{"gen": {int(journal_gen)}}},\n'
                        if journal_gen is not None else "")
        body = (f'{{\n"checksums": {checksums},\n{journal_line}'
                f'"v1": {v1_s},\n"v2": {v2_s}\n}}\n')
        fi.fire("checkpoint.write", payload=body)
        tmp = f"{self._path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(body)
            f.flush()
            fi.fire("checkpoint.fsync")
            os.fsync(f.fileno())
            _metrics.CHECKPOINT_FSYNCS.labels("file").inc()
        # a crash here is a TORN write: the fsync'd tmp exists but the
        # rename never ran — the live checkpoint must remain the previous
        # intact version (asserted by the torn-write drill)
        fi.fire("checkpoint.write.torn")
        os.replace(tmp, self._path)
        # rename durability: fsyncing only the tmp file persists the
        # BYTES, not the directory entry — a power cut after the rename
        # could still resurrect the old file. fsync the directory too.
        _fsync_dir(self._state_dir)
        self.last_journal_gen = int(journal_gen or 0)
        _metrics.CHECKPOINT_WRITES.inc()


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return  # platforms without directory fds: best-effort
    try:
        os.fsync(fd)
        _metrics.CHECKPOINT_FSYNCS.labels("dir").inc()
    finally:
        os.close(fd)


def _journal_gen_of(raw: Dict) -> int:
    j = raw.get("journal")
    if not isinstance(j, dict):
        return 0
    try:
        return int(j.get("gen", 0))
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------------------
# Append-only journal checkpoint (feature gate: JournalCheckpoint)
# ---------------------------------------------------------------------------
#
# WAL discipline over the rewrite format above: each write-ahead/commit
# transition APPENDS one CRC-framed record to ``checkpoint.journal``
# instead of rewriting the whole ``checkpoint.json``; recovery replays
# the journal over the last compacted base; a size/record-count trigger
# compacts (rewrites the base atomically via CheckpointManager.write —
# same tmp+rename+dir-fsync+torn-write machinery — then truncates the
# journal). Records are generation-stamped so a crash BETWEEN the
# compacted base landing and the journal truncate is safe: the new base
# carries gen+1, every journal record still carries gen, and replay
# skips stale generations instead of double-applying them.
#
# Record framing — one line per record::
#
#     <crc32 hex8> <canonical JSON body>\n
#
# body = {"gen": G, "seq": N, "op": "put"|"del", "uid": U[, "entry": E]}
#
# A torn tail (partial last line, CRC mismatch at the end) is truncated
# and forgotten — the committer whose append tore never got its ack, so
# recovery owes it nothing (write-ahead semantics). Corruption strictly
# BEFORE intact records is different: the intact suffix cannot be
# trusted to be causally complete, so replay stops at the first bad
# record and the damaged journal is quarantined for postmortem.

JOURNAL_FILENAME = "checkpoint.journal"

#: compaction triggers (record count OR encoded bytes); also the
#: JOURNAL_BLOAT threshold tools/doctor.py warns at.
JOURNAL_COMPACT_MAX_RECORDS = 512
JOURNAL_COMPACT_MAX_BYTES = 1 << 20

JOURNAL_OP_PUT = "put"
JOURNAL_OP_DEL = "del"


@dataclass
class JournalRecord:
    gen: int
    seq: int
    op: str                          # put | del
    uid: str
    entry: Optional[Dict] = None     # ClaimEntry.to_obj() for put


class JournalDecodeError(ValueError):
    pass


def encode_journal_record(rec: JournalRecord) -> str:
    body: Dict = {"gen": rec.gen, "seq": rec.seq, "op": rec.op,
                  "uid": rec.uid}
    if rec.op == JOURNAL_OP_PUT:
        body["entry"] = rec.entry
    s = _canonical(body)
    return f"{zlib.crc32(s.encode()):08x} {s}\n"


def decode_journal_record(line: str) -> JournalRecord:
    if not line.endswith("\n"):
        raise JournalDecodeError("partial line (no trailing newline)")
    raw = line[:-1]
    crc_hex, sep, body = raw.partition(" ")
    if not sep or len(crc_hex) != 8:
        raise JournalDecodeError("malformed frame")
    try:
        want = int(crc_hex, 16)
    except ValueError as e:
        raise JournalDecodeError(f"bad CRC field: {e}") from e
    if zlib.crc32(body.encode()) != want:
        raise JournalDecodeError("CRC mismatch")
    try:
        obj = json.loads(body)
    except json.JSONDecodeError as e:
        raise JournalDecodeError(f"invalid JSON body: {e}") from e
    op = obj.get("op")
    if op not in (JOURNAL_OP_PUT, JOURNAL_OP_DEL):
        raise JournalDecodeError(f"unknown op {op!r}")
    return JournalRecord(gen=int(obj.get("gen", 0)),
                         seq=int(obj.get("seq", 0)), op=op,
                         uid=str(obj.get("uid", "")),
                         entry=obj.get("entry"))


def scan_journal(path: str):
    """Pure, read-only journal scan (shared with tools/doctor.py).

    Returns ``(records, good_bytes, bad_index)``: decoded records up to
    the first undecodable line, the byte offset of the end of the last
    good record (the torn-tail truncation point), and the 0-based index
    of the first bad line (None = clean). Missing file = empty journal.
    """
    records: List[JournalRecord] = []
    good_bytes = 0
    bad_index = None
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return records, 0, None
    pos = 0
    i = 0
    while pos < len(data):
        nl = data.find(b"\n", pos)
        chunk = data[pos:] if nl < 0 else data[pos:nl + 1]
        try:
            records.append(decode_journal_record(chunk.decode()))
        except (JournalDecodeError, UnicodeDecodeError):
            bad_index = i
            break
        pos += len(chunk)
        good_bytes = pos
        i += 1
    return records, good_bytes, bad_index


def replay_records(cp: Checkpoint, base_gen: int,
                   records: List[JournalRecord]) -> tuple:
    """Apply ``records`` with gen == base_gen onto ``cp`` in order.
    Returns ``(applied, stale)`` counts. Pure (used by doctor too)."""
    applied = stale = 0
    for rec in records:
        if rec.gen != base_gen:
            stale += 1
            continue
        if rec.op == JOURNAL_OP_PUT:
            cp.claims[rec.uid] = ClaimEntry.from_obj(rec.entry or {})
        else:
            cp.claims.pop(rec.uid, None)
        applied += 1
    return applied, stale


class JournalCheckpointManager:
    """Checkpoint persistence as base + append-only journal.

    Owns both files. ``recover()`` replays the journal over the base and
    then compacts, so every restart begins from a fresh base and an
    empty journal — byte-compatible (same v1/v2 payload bytes) with what
    the rewrite-format manager would persist for the same claim state,
    which is exactly what the format-migration drills assert. Appends
    after recovery go through :meth:`append`; callers coalesce them via
    :class:`GroupCommitWriter`.
    """

    def __init__(self, state_dir: str,
                 compact_max_records: int = JOURNAL_COMPACT_MAX_RECORDS,
                 compact_max_bytes: int = JOURNAL_COMPACT_MAX_BYTES):
        self.base = CheckpointManager(state_dir)
        self._state_dir = state_dir
        self._jpath = os.path.join(state_dir, JOURNAL_FILENAME)
        self._compact_max_records = compact_max_records
        self._compact_max_bytes = compact_max_bytes
        self._gen = 0
        self._seq = 0
        self._jbytes = 0
        self._jrecords = 0
        self._jfile = None

    @property
    def journal_path(self) -> str:
        return self._jpath

    @property
    def generation(self) -> int:
        return self._gen

    @property
    def journal_records(self) -> int:
        return self._jrecords

    # -- recovery -----------------------------------------------------------

    def recover(self) -> Checkpoint:
        """Base (quarantining if corrupt) + journal replay + compact.

        Idempotent at every crash boundary: re-crashing anywhere inside
        recovery leaves base+journal in a state this same procedure
        resolves to the same claim set (stale-generation skip covers the
        compact/truncate window; torn-tail truncate covers append)."""
        cp = self.base.read_or_quarantine()
        base_gen = self.base.last_journal_gen
        records, good_bytes, bad_index = scan_journal(self._jpath)
        if bad_index is not None:
            tail_only = bad_index == len(records) and self._is_tail_damage(
                good_bytes)
            if tail_only:
                # torn tail: the committer never acked; drop it silently
                log.warning(
                    "journal %s: torn tail truncated at byte %d "
                    "(%d intact records)", self._jpath, good_bytes,
                    len(records))
            else:
                # mid-file damage: records after it can't be trusted to
                # be causally complete — quarantine for postmortem and
                # recover from the intact prefix only
                qpath = self._quarantine_journal()
                _metrics.CHECKPOINT_QUARANTINED.inc()
                log.error(
                    "JOURNAL CORRUPT: %s record %d undecodable mid-file "
                    "— quarantined to %s; recovering from the %d-record "
                    "intact prefix (later transitions may be lost; the "
                    "cleanup sweep and idempotent re-prepare will "
                    "reconverge)", self._jpath, bad_index, qpath,
                    len(records))
        applied, stale = replay_records(cp, base_gen, records)
        if stale:
            log.info("journal %s: skipped %d stale-generation records "
                     "(base gen %d moved past them mid-compaction)",
                     self._jpath, stale, base_gen)
        self._gen = base_gen
        # compact unconditionally: recovery ends with a fresh base and an
        # empty journal, making the recovered state byte-identical to the
        # rewrite format's and re-crash during recovery a no-op
        self.compact(cp)
        self._open_journal()
        return cp

    def _is_tail_damage(self, good_bytes: int) -> bool:
        """True when the undecodable region is the LAST thing in the
        file (no intact record follows it) — the torn-append signature."""
        try:
            with open(self._jpath, "rb") as f:
                f.seek(good_bytes)
                rest = f.read()
        except OSError:
            return True
        # any intact record after the damage ⇒ mid-file corruption
        for line in rest.splitlines(keepends=True):
            try:
                decode_journal_record(line.decode())
            except (JournalDecodeError, UnicodeDecodeError):
                continue
            return False
        return True

    def _quarantine_journal(self) -> str:
        import shutil
        n = 1
        while os.path.exists(f"{self._jpath}.corrupt-{n}"):
            n += 1
        qpath = f"{self._jpath}.corrupt-{n}"
        try:
            shutil.copyfile(self._jpath, qpath)
        except OSError:
            log.warning("could not preserve corrupt journal at %s",
                        qpath, exc_info=True)
            return "<copy failed>"
        return qpath

    # -- append path --------------------------------------------------------

    def _open_journal(self) -> None:
        if self._jfile is None:
            self._jfile = open(self._jpath, "a")

    def append(self, ops) -> int:
        """Append ``[(op, uid, entry_obj_or_None), ...]`` as one write +
        one fsync. Returns the record count. Called only from the
        group-commit writer thread (single writer — no locking here)."""
        self._open_journal()
        lines = []
        for op, uid, entry in ops:
            self._seq += 1
            lines.append(encode_journal_record(JournalRecord(
                gen=self._gen, seq=self._seq, op=op, uid=uid,
                entry=entry)))
        data = "".join(lines)
        data = fi.fire("journal.append", payload=data)
        self._jfile.write(data)
        self._jfile.flush()
        os.fsync(self._jfile.fileno())
        _metrics.CHECKPOINT_FSYNCS.labels("journal").inc()
        self._jbytes += len(data)
        self._jrecords += len(lines)
        _metrics.JOURNAL_RECORDS.set(self._jrecords)
        return len(lines)

    def needs_compaction(self) -> bool:
        return (self._jrecords >= self._compact_max_records
                or self._jbytes >= self._compact_max_bytes)

    def compact(self, cp: Checkpoint) -> None:
        """Rewrite the base at gen+1 (atomic, reusing the torn-write and
        quarantine machinery of CheckpointManager.write) and truncate
        the journal. Crash-safe at every boundary:

        - before the rename lands: old base + old journal, nothing lost;
        - after the rename, before the truncate (``journal.compact``
          fires here): new base gen+1, journal full of gen records —
          replay skips them all as stale;
        - after the truncate: steady state.
        """
        t0 = _time.monotonic()
        self._gen += 1
        self.base.write(cp, journal_gen=self._gen)
        fi.fire("journal.compact")
        if self._jfile is not None:
            self._jfile.truncate(0)
            self._jfile.flush()
        else:
            with open(self._jpath, "w"):
                pass
        self._seq = 0
        self._jbytes = 0
        self._jrecords = 0
        _metrics.JOURNAL_RECORDS.set(0)
        _metrics.JOURNAL_COMPACTION_SECONDS.observe(
            _time.monotonic() - t0)

    def close(self) -> None:
        if self._jfile is not None:
            try:
                self._jfile.close()
            finally:
                self._jfile = None


def fold_journal_into_base(state_dir: str) -> bool:
    """Migration: journal format → rewrite format. When the gate is off
    but a journal file exists (a downgrade after running journaled), fold
    its surviving records into the base and remove it, so the rewrite
    manager — and any pre-journal reader — sees one healthy
    checkpoint.json. Returns True when a fold happened."""
    jpath = os.path.join(state_dir, JOURNAL_FILENAME)
    if not os.path.exists(jpath):
        return False
    mgr = JournalCheckpointManager(state_dir)
    try:
        mgr.recover()   # replay + compact: journal now empty
    finally:
        mgr.close()
    os.unlink(jpath)
    log.info("folded checkpoint journal into base (%s removed): "
             "JournalCheckpoint gate is off", jpath)
    return True


class _CommitTicket:
    """One committer's stake in a group commit."""

    __slots__ = ("_ev", "_err")

    def __init__(self):
        self._ev = threading.Event()
        self._err = None

    def done(self, err=None) -> None:
        self._err = err
        self._ev.set()

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self._ev.wait(timeout):
            raise TimeoutError("journal group commit did not complete")
        if self._err is not None:
            raise self._err


class GroupCommitWriter:
    """Single journal-writer thread coalescing appends from concurrent
    batches into one fsync (classic group commit: the leader drains the
    queue while fsyncing; followers that arrive meanwhile ride the next
    round). A bounded latency window (~2 ms) lets the writer wait for
    stragglers ONLY while other batches are known in flight
    (``batch_begin``/``batch_end`` hints), so a lone committer never
    pays the window.

    ``enqueue`` is called under DeviceState's state lock (preserving
    journal order = memory order); ``Ticket.wait`` happens OUTSIDE it.
    Compaction runs on the writer thread between commits, against a
    snapshot the owner supplies (it takes the state lock itself).
    """

    def __init__(self, mgr: JournalCheckpointManager, snapshot,
                 window_s: float = 0.002):
        self._mgr = mgr
        self._snapshot = snapshot          # () -> Checkpoint, takes state lock
        self._window_s = window_s
        self._cond = threading.Condition()
        self._queue: List[tuple] = []      # [(ops, ticket), ...]
        self._inflight = 0
        self._stopped = False
        self._held = False                 # deterministic test hook
        # lazy start: idle plugins (fleet harnesses build many) don't
        # pay a thread until their first commit
        self._thread: Optional[threading.Thread] = None

    def _ensure_thread(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="journal-group-commit", daemon=True)
            self._thread.start()

    # -- committer side -----------------------------------------------------

    def batch_begin(self) -> None:
        with self._cond:
            self._inflight += 1

    def batch_end(self) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            self._cond.notify_all()

    def enqueue(self, ops) -> _CommitTicket:
        """Queue ``[(op, uid, entry_obj), ...]`` for the next group
        commit. Call under the state lock; ``wait()`` the ticket after
        releasing it."""
        t = _CommitTicket()
        with self._cond:
            if self._stopped:
                t.done(RuntimeError("journal writer is stopped"))
                return t
            self._ensure_thread()
            self._queue.append((list(ops), t))
            self._cond.notify_all()
        return t

    # -- test hooks ---------------------------------------------------------

    def hold(self) -> None:
        """Pause draining (tests enqueue from N threads, then release
        and assert ONE fsync served them all)."""
        with self._cond:
            self._held = True

    def release(self) -> None:
        with self._cond:
            self._held = False
            self._cond.notify_all()

    # -- writer thread ------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while (not self._queue or self._held) and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._queue:
                    return
                if not self._held:
                    # bounded straggler window: only worth waiting when
                    # more batches are in flight than are already queued
                    deadline = _time.monotonic() + self._window_s
                    while (self._inflight > len(self._queue)
                           and not self._stopped):
                        left = deadline - _time.monotonic()
                        if left <= 0:
                            break
                        self._cond.wait(left)
                batch = self._queue
                self._queue = []
            t0 = _time.monotonic()
            ops = [op for ops_, _ in batch for op in ops_]
            err = None
            try:
                n = self._mgr.append(ops)
                _metrics.JOURNAL_GROUP_COMMIT_RECORDS.observe(n)
            except BaseException as e:  # chaos-ok: delivered to every waiting ticket, whose wait() re-raises it on the calling batch
                err = e
            dt = _time.monotonic() - t0
            for _, ticket in batch:
                _metrics.JOURNAL_APPEND_SECONDS.observe(dt)
                ticket.done(err)
            if err is None and self._mgr.needs_compaction():
                try:
                    self._mgr.compact(self._snapshot())
                except Exception:  # noqa: BLE001
                    # a failed compaction is survivable: the journal
                    # keeps growing and the next round retries
                    log.exception("journal compaction failed; will retry")
                    _metrics.SWALLOWED_ERRORS.labels("journal.compact").inc()

    def stop(self, timeout: float = 10.0) -> None:
        """Drain outstanding commits and stop the writer thread."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
