"""Versioned, checksummed claim checkpoints with V1/V2 dual-write.

Reference analog: cmd/gpu-kubelet-plugin/{checkpoint.go:26-138,
checkpointv.go:25-98} — a kubelet-checkpointmanager JSON checkpoint with
checksums, written in both a legacy V1 and current V2 layout so upgrades
and *downgrades* both find a readable file (exercised by the reference's
up/downgrade bats tests).

Layout here: one JSON file ``checkpoint.json`` containing both versions::

    {
      "v1": {"claims": {...}},          # legacy: flat prepared-devices list
      "v2": {"claims": {...}},          # current: adds per-claim state machine
      "checksums": {"v1": <crc32>, "v2": <crc32>}
    }

Readers prefer V2 and fall back to V1 (nonstrict: unknown fields in a
newer writer's V2 are ignored on the V1 path). Writes are atomic
(tmp+rename+fsync). Checksum mismatch → checkpoint corruption error, the
caller treats the file as absent-but-alarming (it refuses to guess).
"""

from __future__ import annotations

import copy
import json
import logging
import os
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg import metrics as _metrics

log = logging.getLogger(__name__)

fi.register("checkpoint.read",
            "raw checkpoint file contents on read (corrupt=CRC/JSON "
            "damage, fail=unreadable file)")
fi.register("checkpoint.write",
            "checkpoint serialization before the tmp file is written "
            "(fail with OSError(ENOSPC) models a full disk)")
fi.register("checkpoint.fsync",
            "the fsync of the checkpoint tmp file (fail=ENOSPC at "
            "flush time)")
fi.register("checkpoint.write.torn",
            "between the fsync'd tmp file and the atomic rename "
            "(crash here = a torn write: tmp left behind, the live "
            "checkpoint must stay intact)")

# Claim prepare states (reference device_state.go:231-283)
PREPARE_STARTED = "PrepareStarted"
PREPARE_COMPLETED = "PrepareCompleted"


class CheckpointCorruptionError(RuntimeError):
    pass


@dataclass
class PreparedDevice:
    """One prepared device recorded in the checkpoint.

    ``canonical_name`` alone must be enough to recover teardown identity
    after a crash (the MigSpecTuple-from-name contract, SURVEY.md §2.3).
    """

    canonical_name: str
    request: str                     # DRA request name this satisfied
    cdi_device_ids: List[str] = field(default_factory=list)
    device_type: str = "chip"        # chip | subslice | shared | vfio |
                                     # channel | daemon
    live_uuid: str = ""              # live sub-slice uuid (informational)
    devfs_path: str = ""
    pool: str = ""                   # allocation result's pool, echoed to
                                     # kubelet (reference device_state.go:738)
    #: the ALLOCATED device name when it differs from the canonical
    #: identity actually created — a dynamic PROFILE claim allocates
    #: ``tpu-i-prof-<id>-<k>`` but the checkpoint journals the placed
    #: ``tpu-i-ss-<id>-<start>`` partition (the one parser recovery
    #: needs); this field preserves the allocation-side name for
    #: kubelet echo and diagnostics. "" = same as canonical_name.
    source_device: str = ""

    def to_obj(self) -> Dict:
        out = {
            "canonicalName": self.canonical_name,
            "request": self.request,
            "cdiDeviceIDs": list(self.cdi_device_ids),
            "deviceType": self.device_type,
            "liveUUID": self.live_uuid,
            "devfsPath": self.devfs_path,
            "pool": self.pool,
        }
        if self.source_device:
            # written only when set: checkpoints without dynamic claims
            # stay byte-identical to the previous writer's layout (and a
            # downgraded nonstrict reader simply ignores the key)
            out["sourceDevice"] = self.source_device
        return out

    @staticmethod
    def from_obj(d: Dict) -> "PreparedDevice":
        return PreparedDevice(
            canonical_name=d.get("canonicalName", ""),
            request=d.get("request", ""),
            cdi_device_ids=list(d.get("cdiDeviceIDs") or []),
            device_type=d.get("deviceType", "chip"),
            live_uuid=d.get("liveUUID", ""),
            devfs_path=d.get("devfsPath", ""),
            pool=d.get("pool", ""),
            source_device=d.get("sourceDevice", ""),
        )


def backfill_pools(entry: "ClaimEntry", claim) -> None:
    """Fill empty ``pool`` on checkpointed devices from the live claim's
    allocation results. Checkpoints written before the pool field existed
    replay with pool="" on the idempotent re-prepare path, and kubelet
    matches prepared devices by (pool, device) — so upgrades must heal
    in-place (reference device_state.go:738 always echoes result.Pool)."""
    pools = {r.device: r.pool for r in claim.results}
    for pd in entry.prepared_devices:
        if not pd.pool:
            pd.pool = pools.get(pd.canonical_name, "")


@dataclass
class ClaimEntry:
    claim_uid: str
    claim_name: str = ""
    namespace: str = ""
    state: str = PREPARE_STARTED
    prepared_devices: List[PreparedDevice] = field(default_factory=list)

    def to_obj(self) -> Dict:
        return {
            "claimUID": self.claim_uid,
            "claimName": self.claim_name,
            "namespace": self.namespace,
            "state": self.state,
            "preparedDevices": [d.to_obj() for d in self.prepared_devices],
        }

    @staticmethod
    def from_obj(d: Dict) -> "ClaimEntry":
        return ClaimEntry(
            claim_uid=d.get("claimUID", ""),
            claim_name=d.get("claimName", ""),
            namespace=d.get("namespace", ""),
            state=d.get("state", PREPARE_STARTED),
            prepared_devices=[PreparedDevice.from_obj(x)
                              for x in d.get("preparedDevices") or []],
        )


@dataclass
class Checkpoint:
    claims: Dict[str, ClaimEntry] = field(default_factory=dict)  # by claim UID

    def deepcopy(self) -> "Checkpoint":
        return Checkpoint(claims={k: copy.deepcopy(v) for k, v in self.claims.items()})

    # -- queries used by the overlap guard ---------------------------------

    def prepared_device_owners(self) -> Dict[str, str]:
        """canonical device name -> owning claim UID, for claims in
        PrepareCompleted (the overlap guard, device_state.go:1116-1154)."""
        out: Dict[str, str] = {}
        for uid, entry in self.claims.items():
            if entry.state != PREPARE_COMPLETED:
                continue
            for dev in entry.prepared_devices:
                out[dev.canonical_name] = uid
        return out


def _canonical(payload) -> str:
    """The checksum-canonical serialization of a version payload.

    This exact form (sort_keys, default separators) is a compatibility
    contract: every reader ever shipped — including downgraded ones —
    verifies a version by re-serializing the parsed payload this way and
    crc32'ing it. Fully compact separators would shrink the file a bit
    further but would invalidate every stored checksum for old readers
    (and vice versa), so the payload bytes stay canonical; the byte win
    comes from writing each payload flat exactly once instead of
    pretty-printing the whole envelope with indent=1."""
    return json.dumps(payload, sort_keys=True)


def _crc(payload) -> int:
    return zlib.crc32(_canonical(payload).encode())


class CheckpointManager:
    """Owns the checkpoint file. Callers serialize via the cp flock held by
    DeviceState; this class only does (de)serialization + atomicity."""

    FILENAME = "checkpoint.json"

    def __init__(self, state_dir: str):
        self._path = os.path.join(state_dir, self.FILENAME)
        os.makedirs(state_dir, exist_ok=True)

    @property
    def path(self) -> str:
        return self._path

    def ensure_exists(self) -> None:
        if not os.path.exists(self._path):
            self.write(Checkpoint())

    def read(self) -> Checkpoint:
        try:
            with open(self._path) as f:
                text = f.read()
        except FileNotFoundError:
            return Checkpoint()
        text = fi.fire("checkpoint.read", payload=text)
        try:
            raw = json.loads(text)
        except json.JSONDecodeError as e:
            raise CheckpointCorruptionError(f"{self._path}: invalid JSON: {e}") from e
        checksums = raw.get("checksums") or {}
        for version in ("v2", "v1"):
            payload = raw.get(version)
            if payload is None:
                continue
            if _crc(payload) != checksums.get(version):
                raise CheckpointCorruptionError(
                    f"{self._path}: {version} checksum mismatch"
                )
            claims = self._claims_from_payload(payload, version)
            return Checkpoint(claims=claims)
        return Checkpoint()

    @staticmethod
    def _claims_from_payload(payload: Dict, version: str) -> Dict[str, ClaimEntry]:
        claims: Dict[str, ClaimEntry] = {}
        for uid, e in (payload.get("claims") or {}).items():
            entry = ClaimEntry.from_obj(e)
            if version == "v1" and "state" not in e:
                # legacy layout records only completed claims
                entry.state = PREPARE_COMPLETED
            claims[uid] = entry
        return claims

    # -- corruption recovery (the no-crash-loop contract) -------------------

    def read_or_quarantine(self) -> Checkpoint:
        """Read, but never crash-loop on a corrupt file: quarantine it to
        ``<path>.corrupt-<n>``, log loudly, count it in
        ``dra_checkpoint_quarantined_total``, and continue from the best
        salvageable state — a version whose checksum still verifies
        (readers prefer v2; a damaged v2 falls back to an intact legacy
        v1, which holds every *completed* claim) — or empty when nothing
        verifies. The salvaged state is immediately re-written so the
        next reader sees a healthy file."""
        try:
            return self.read()
        except CheckpointCorruptionError as e:
            salvaged = self._salvage()
            # Quarantine is a COPY: the corrupt original must stay at the
            # live path until the salvaged rewrite's atomic replace lands —
            # renaming it away first would leave NO checkpoint at all if
            # the rewrite fails (ENOSPC is one of the very faults drilled
            # here) or the process dies in the window, silently forgetting
            # every prepared claim on the next (empty) read.
            qpath = self._quarantine_copy()
            _metrics.CHECKPOINT_QUARANTINED.inc()
            log.error(
                "CHECKPOINT CORRUPT: %s — quarantined to %s; continuing "
                "from %s state (prepared-claim history may be incomplete; "
                "the cleanup sweep and idempotent re-prepare will "
                "reconverge)", e, qpath,
                f"salvaged {len(salvaged.claims)}-claim" if salvaged is not None
                else "empty")
            cp = salvaged if salvaged is not None else Checkpoint()
            self.write(cp)
            return cp

    def _salvage(self) -> Optional[Checkpoint]:
        """Best-effort recovery of any version whose checksum still
        verifies (v2 preferred). None when the JSON itself is broken or
        no version survives."""
        try:
            with open(self._path) as f:
                raw = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        checksums = raw.get("checksums") or {}
        for version in ("v2", "v1"):
            payload = raw.get(version)
            if payload is None or _crc(payload) != checksums.get(version):
                continue
            return Checkpoint(
                claims=self._claims_from_payload(payload, version))
        return None

    def _quarantine_copy(self) -> str:
        """Preserve the corrupt bytes for postmortem WITHOUT touching the
        live path (best-effort: on a full disk the copy may fail, which
        must not block recovery)."""
        import shutil
        n = 1
        while os.path.exists(f"{self._path}.corrupt-{n}"):
            n += 1
        qpath = f"{self._path}.corrupt-{n}"
        try:
            shutil.copyfile(self._path, qpath)
        except OSError:
            log.warning("could not preserve corrupt checkpoint at %s",
                        qpath, exc_info=True)
            return "<copy failed>"
        return qpath

    def write(self, cp: Checkpoint) -> None:
        v2 = {"claims": {uid: e.to_obj() for uid, e in cp.claims.items()}}
        # V1 (legacy layout): no state machine — only *completed* claims
        # with their device names, the shape a pre-state-machine downgrade
        # reader expects (in-flight PrepareStarted entries are deliberately
        # absent: the legacy reader would have no rollback logic for them).
        v1 = {
            "claims": {
                uid: {
                    "claimUID": e.claim_uid,
                    "claimName": e.claim_name,
                    "namespace": e.namespace,
                    "preparedDevices": [d.to_obj() for d in e.prepared_devices],
                }
                for uid, e in cp.claims.items()
                if e.state == PREPARE_COMPLETED
            }
        }
        # Serialize each version payload exactly ONCE: the same bytes
        # are checksummed and spliced verbatim into the envelope (the
        # old path serialized every payload twice — once in _crc, once
        # inside json.dump — and pretty-printed with indent=1, paying
        # ~40% more bytes per fsync). The envelope keeps a readable
        # top level: one line per section.
        v1_s = _canonical(v1)
        v2_s = _canonical(v2)
        checksums = json.dumps(
            {"v1": zlib.crc32(v1_s.encode()), "v2": zlib.crc32(v2_s.encode())},
            separators=(",", ":"))
        body = (f'{{\n"checksums": {checksums},\n'
                f'"v1": {v1_s},\n"v2": {v2_s}\n}}\n')
        fi.fire("checkpoint.write", payload=body)
        tmp = f"{self._path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(body)
            f.flush()
            fi.fire("checkpoint.fsync")
            os.fsync(f.fileno())
        # a crash here is a TORN write: the fsync'd tmp exists but the
        # rename never ran — the live checkpoint must remain the previous
        # intact version (asserted by the torn-write drill)
        fi.fire("checkpoint.write.torn")
        os.replace(tmp, self._path)
        _metrics.CHECKPOINT_WRITES.inc()
