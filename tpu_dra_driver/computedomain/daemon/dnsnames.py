"""Stable worker-name ↔ IP mappings (the /etc/hosts rewriting mechanism).

Reference analog: cmd/compute-domain-daemon/dnsnames.go:34-216 — the IMEX
nodes-config must stay *static* while pod IPs churn, so the daemon writes
stable DNS names (``compute-domain-daemon-%04d``) into /etc/hosts and
rewrites only its own marker-delimited block, idempotently.

TPU use: ``TPU_WORKER_HOSTNAMES`` injected into workload containers names
peers as ``cd-daemon-%04d`` (index = the stable clique index, which is the
worker id); this module maintains the hosts-file block mapping those names
to the per-node daemon IPs (daemons run with hostNetwork, so daemon IP ==
node IP — worker identity is per *host*, matching TPU-VM semantics).
"""

from __future__ import annotations

import os
from typing import Dict

BEGIN_MARKER = "# BEGIN tpu-dra-driver compute-domain workers"
END_MARKER = "# END tpu-dra-driver compute-domain workers"

WORKER_NAME_FORMAT = "cd-daemon-{index:04d}"


def worker_name(index: int) -> str:
    return WORKER_NAME_FORMAT.format(index=index)


def render_block(mapping: Dict[int, str]) -> str:
    """mapping: worker index -> IP address."""
    lines = [BEGIN_MARKER]
    for index in sorted(mapping):
        lines.append(f"{mapping[index]}\t{worker_name(index)}")
    lines.append(END_MARKER)
    return "\n".join(lines) + "\n"


def update_hosts_file(path: str, mapping: Dict[int, str]) -> bool:
    """Idempotently replace (or append) our marker block in ``path``.
    Returns True when the file changed."""
    try:
        with open(path) as f:
            content = f.read()
    except FileNotFoundError:
        content = ""
    block = render_block(mapping)
    begin = content.find(BEGIN_MARKER)
    end = content.find(END_MARKER)
    if begin != -1 and end != -1:
        end_of_block = end + len(END_MARKER)
        if end_of_block < len(content) and content[end_of_block] == "\n":
            end_of_block += 1
        new_content = content[:begin] + block + content[end_of_block:]
    else:
        sep = "" if (not content or content.endswith("\n")) else "\n"
        new_content = content + sep + block
    if new_content == content:
        return False
    import threading
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "w") as f:
        f.write(new_content)
    os.replace(tmp, path)
    return True


def parse_block(path: str) -> Dict[int, str]:
    """Read back our block: worker index -> IP (test/debug helper)."""
    try:
        with open(path) as f:
            content = f.read()
    except FileNotFoundError:
        return {}
    out: Dict[int, str] = {}
    inside = False
    for line in content.splitlines():
        if line == BEGIN_MARKER:
            inside = True
            continue
        if line == END_MARKER:
            break
        if inside and line.strip():
            ip, _, name = line.partition("\t")
            prefix = WORKER_NAME_FORMAT.split("{")[0]
            if name.startswith(prefix):
                out[int(name[len(prefix):])] = ip
    return out
