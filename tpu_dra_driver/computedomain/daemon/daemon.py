"""The compute-domain daemon: per-node-per-CD membership + readiness agent.

Reference analog: cmd/compute-domain-daemon/main.go — there the daemon
renders IMEX configs, supervises the ``nvidia-imex`` child with a watchdog,
SIGUSR1-reloads it on peer changes, and serves a ``check`` readiness
subcommand querying ``nvidia-imex-ctl``.

TPU redesign: **no child process exists** — libtpu in the *workload*
containers drives ICI directly. The daemon reduces to:

1. label its pod with the clique id (physical ICI slice id from tpulib),
2. join the ComputeDomainClique (stable gap-filled index = worker id),
3. maintain the worker hosts mapping (dnsnames) and a rendered
   ``worker-env`` snapshot as peers change (the IMEX-config-reload analog,
   minus the process to signal),
4. readiness (``check``): our clique entry exists and every member is in
   the hosts mapping — then report Ready into the clique,
5. on fabric (ICI) health errors: crash when CrashOnICIFabricErrors is
   enabled so Kubernetes restarts the pod and the fabric re-rendezvouses —
   the reference's crash-on-NVLink-error semantics,
6. on shutdown: leave the clique.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from tpu_dra_driver.computedomain import DRIVER_NAMESPACE
from tpu_dra_driver.computedomain.daemon.clique import CliqueMembership
from tpu_dra_driver.computedomain.daemon.dnsnames import (
    update_hosts_file,
    worker_name,
)
from tpu_dra_driver.kube.client import ABORT, ClientSets
from tpu_dra_driver.kube.errors import NotFoundError
from tpu_dra_driver.kube.informer import Informer
from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg import featuregates as fg
from tpu_dra_driver.pkg import tracing
from tpu_dra_driver.pkg.metrics import SWALLOWED_ERRORS
from tpu_dra_driver.tpulib.interface import HealthEvent, HealthEventKind, TpuLib

log = logging.getLogger(__name__)

fi.register("daemon.clique.render",
            "one hosts/worker-env re-render pass (fail = render dies "
            "mid-burst; the render loop must retry until the files "
            "reflect the latest membership)")

CLIQUE_ID_LABEL_KEY = "resource.tpu.google.com/cliqueID"


@dataclass
class DaemonConfig:
    cd_uid: str
    cd_name: str
    cd_namespace: str
    node_name: str
    pod_name: str
    pod_ip: str
    # The shared host path the CD plugin bind-mounts into workload
    # containers (CdPluginConfig.hosts_file_dir + "/hosts") — NOT the
    # daemon pod's own /etc/hosts, which workloads never see.
    hosts_file: str = "/run/tpu-dra/hosts"
    worker_env_file: str = "/run/tpu-dra/worker-env.json"
    #: the per-CD run directory THIS daemon owns (cmd cd_run_dir). When
    #: set, a graceful stop removes it: the hostPath outlives the pod,
    #: so a CD teardown that leaves hosts/worker-env behind accumulates
    #: one corpse dir per CD ever scheduled on the node — the 10k-node
    #: compressed-week soak's checkpoint_bytes sentinel measured the
    #: drift (seed 20260804: +~930 bytes/epoch, monotone across all 7
    #: epochs). Empty = unscoped legacy layout, never deleted.
    run_dir: str = ""
    gates: fg.FeatureGates = field(default_factory=fg.FeatureGates)


class FabricError(RuntimeError):
    """Raised (crashing the daemon) on ICI fabric errors when
    CrashOnICIFabricErrors is enabled."""


class ComputeDomainDaemon:
    def __init__(self, clients: ClientSets, lib: TpuLib, config: DaemonConfig):
        self._clients = clients
        self._lib = lib
        self._config = config
        self.clique_id = lib.slice_id()
        self.membership = CliqueMembership(
            clients.compute_domain_cliques, config.cd_uid, self.clique_id,
            config.node_name, config.pod_ip)
        self.index: Optional[int] = None
        self._informer: Optional[Informer] = None
        self._unsub_health = None
        self._mu = threading.Lock()
        self._render_mu = threading.Lock()  # serializes _on_clique_change
        # Render coalescing: clique watch events mark dirty; one render
        # thread folds a burst (a multislice CD sees every sibling
        # clique's churn) into a single re-render of hosts/worker-env +
        # readiness re-check, instead of one file rewrite per event.
        self._dirty = threading.Event()
        self._render_stop = threading.Event()
        self._render_thread: Optional[threading.Thread] = None
        self._fabric_error: Optional[HealthEvent] = None
        self._num_slices = 1
        self._last_worker_env: Optional[Dict[str, str]] = None
        self._on_fabric_error_cb = None
        # The CD's trace context (traceparent annotation stamped by the
        # controller), captured when the CD is first read: clique
        # join/render spans from this process land in the same trace as
        # the controller's cd.rendezvous span.
        self._trace_ctx = None
        # Set on fatal fabric errors. The production entrypoint waits on
        # this and exits nonzero so Kubernetes restarts the pod — raising
        # from a health-callback thread could never kill the process.
        self.fatal = threading.Event()

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._label_pod()
        t_join0 = time.monotonic()
        self.index = self.membership.join()
        self._num_slices = self._cd_num_slices()
        # marker span: this daemon joined its clique (the trace context
        # only becomes known with the CD read above, so the join is
        # recorded retroactively with its measured duration)
        join_span = tracing.start_span(
            "daemon.join", parent=self._trace_ctx,
            attributes={"node": self._config.node_name,
                        "clique": self.clique_id,
                        "index": self.index,
                        "join_ms": round(
                            (time.monotonic() - t_join0) * 1e3, 3)})
        join_span.end()
        self._unsub_health = self._lib.subscribe_health(self._on_health)
        # name-filtered clique informer (reference controller.go:95-133);
        # a multislice CD watches all sibling cliques (the coordinator
        # address in worker-env depends on slice 0's membership)
        if self._num_slices > 1:
            prefix = f"{self._config.cd_uid}."
            name_filter = lambda n: n.startswith(prefix)  # noqa: E731
        else:
            name_filter = lambda n: n == self.membership.name  # noqa: E731
        self._informer = Informer(
            self._clients.compute_domain_cliques,
            namespace=DRIVER_NAMESPACE,
            name_filter=name_filter)
        self._informer.add_handlers(
            on_add=lambda o: self._dirty.set(),
            on_update=lambda old, new: self._dirty.set(),
            on_delete=lambda o: None)
        self._render_thread = threading.Thread(
            target=self._render_loop, daemon=True,
            name=f"cd-daemon-render-{self._config.node_name}")
        self._render_thread.start()
        self._informer.start()
        self._informer.wait_synced()
        self._on_clique_change()
        log.info("cd-daemon started: cd=%s clique=%s index=%s",
                 self._config.cd_uid, self.clique_id, self.index)

    def stop(self) -> None:
        self._render_stop.set()
        self._dirty.set()  # unblock the render loop promptly
        if self._unsub_health:
            self._unsub_health()
        if self._informer:
            self._informer.stop()
        if self._render_thread is not None:
            self._render_thread.join(timeout=2.0)
        self.membership.leave()
        self._cleanup_run_dir()

    def _cleanup_run_dir(self) -> None:
        """Remove the per-CD run dir on graceful stop (CD teardown /
        SIGTERM). Only the rendered derivatives this daemon owns live
        there (hosts, worker-env, ready marker) — all recreated from
        the clique on the next start, so deletion is always safe; a
        crash (SIGKILL) never runs this and the replacement daemon
        reuses the surviving dir."""
        run_dir = self._config.run_dir
        if not run_dir:
            return
        shutil.rmtree(run_dir, ignore_errors=True)

    def set_fabric_error_callback(self, cb) -> None:
        self._on_fabric_error_cb = cb

    # ------------------------------------------------------------------

    def _label_pod(self) -> None:
        """Label our pod with the clique id (reference main.go:528-555)."""
        def mutate(obj):
            labels = obj["metadata"].setdefault("labels", {})
            if labels.get(CLIQUE_ID_LABEL_KEY) == self.clique_id:
                return ABORT
            labels[CLIQUE_ID_LABEL_KEY] = self.clique_id
        try:
            self._clients.pods.retry_update(
                self._config.pod_name, DRIVER_NAMESPACE, mutate)
        except NotFoundError:
            log.warning("own pod %s not found for clique-id labeling",
                        self._config.pod_name)

    # ------------------------------------------------------------------
    # peer-change handling (the IMEX-config-reload analog)
    # ------------------------------------------------------------------

    def _render_loop(self) -> None:
        """Folds event bursts: however many clique events marked dirty
        since the last pass, exactly one re-render runs — reading the
        LATEST membership — before the next wait."""
        while not self._render_stop.is_set():
            if not self._dirty.wait(timeout=0.2):
                continue
            self._dirty.clear()
            try:
                self._on_clique_change()
            except Exception:  # chaos-ok: counted + dirty re-set for retry
                SWALLOWED_ERRORS.labels("daemon.clique.render").inc()
                log.exception("clique re-render failed; will retry")
                # the event that marked dirty is consumed: without a
                # re-set a failed render would strand stale hosts files
                # until the NEXT membership change (which may never come)
                self._render_stop.wait(0.2)    # backoff, stop-interruptible
                self._dirty.set()

    def _on_clique_change(self) -> None:
        # Serialized: fires from both start() and the render thread;
        # concurrent runs would race on the (pid-named) tmp files and could
        # install a stale hosts block.
        with self._render_mu:
            span = tracing.start_span(
                "daemon.clique_render", parent=self._trace_ctx,
                attributes={"node": self._config.node_name,
                            "clique": self.clique_id})
            with tracing.use_span(span), span:
                fi.fire("daemon.clique.render", payload=self._config.cd_uid)
                cq = self.membership.get()
                if cq is None:
                    span.set_attribute("result", "clique-missing")
                    return
                mapping: Dict[int, str] = {d.index: d.ip_address
                                           for d in cq.daemons
                                           if d.index >= 0 and d.ip_address}
                changed = update_hosts_file(self._config.hosts_file, mapping)
                self._write_worker_env(mapping)
                if changed:
                    log.info("hosts mapping updated: %s",
                             {worker_name(i): ip
                              for i, ip in mapping.items()})
                # readiness is not a one-way latch: report NotReady again
                # when the check regresses (e.g. fabric error, peer
                # inconsistency) so the controller stops releasing
                # workloads onto this node
                ready = self.check()
                span.set_attribute("members", len(mapping))
                span.set_attribute("ready", ready)
                if ready:
                    self.membership.set_ready()
                else:
                    from tpu_dra_driver.api.types import STATUS_NOT_READY
                    self.membership.set_status(STATUS_NOT_READY)

    def _write_worker_env(self, mapping: Dict[int, str]) -> None:
        """Render the worker-identity snapshot (debugging + the CD plugin's
        fallback source). The authoritative copy of this data lives in the
        Clique CR; this file is the node-local rendering."""
        topo = self._lib.host_topology()
        names = [worker_name(i) for i in sorted(mapping)]
        env = {
            "TPU_WORKER_ID": str(self.index),
            "TPU_WORKER_HOSTNAMES": ",".join(names),
            "TPU_ACCELERATOR_TYPE": topo.accelerator_type,
            "TPU_TOPOLOGY": topo.topology_string,
            "cliqueID": self.clique_id,
            "computeDomain": self._config.cd_uid,
        }
        if self._num_slices > 1:
            env.update(self._megascale_env())
        if env == self._last_worker_env:
            return  # clique churn with no identity change: skip the IO
        path = self._config.worker_env_file
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(env, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        self._last_worker_env = env

    def _cd_num_slices(self, timeout: float = 2.0) -> int:
        """numSlices from our ComputeDomain's spec. A transient 404 (API
        lag at daemon start) is bridged by WATCHING computedomains and
        re-reading on each event instead of a fixed retry-sleep ladder —
        silently caching 1 would strip a multislice daemon of its wide
        clique watch for its whole life."""
        import time as _time
        # Watch-before-get closes the create/get race: a CD created after
        # the failed get lands as an event that wakes the re-read.
        sub = self._clients.compute_domains.watch()
        try:
            deadline = _time.monotonic() + timeout
            while True:
                try:
                    obj = self._clients.compute_domains.get(
                        self._config.cd_name, self._config.cd_namespace)
                    self._trace_ctx = tracing.from_object(obj)
                    return max(1, int((obj.get("spec") or {})
                                      .get("numSlices", 1)))
                except NotFoundError:
                    pass
                except (ValueError, TypeError):
                    break
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                # Blocks until a computedomains event or the deadline; any
                # event (ours or not) triggers a cheap re-read.
                sub.next(timeout=min(remaining, 0.5))
        finally:
            self._clients.compute_domains.stop_watch(sub)
        log.warning("could not read numSlices for cd %s/%s; assuming 1",
                    self._config.cd_namespace, self._config.cd_name)
        return 1

    def _megascale_env(self) -> Dict[str, str]:
        """Best-effort MEGASCALE_* snapshot for the node-local rendering
        (the authoritative, release-gated copy is computed by the CD
        kubelet plugin at Prepare, via the same shared derivation). While
        the cross-slice world is still forming only the static fields are
        rendered — this file never gates anything."""
        from tpu_dra_driver.computedomain.multislice import (
            MEGASCALE_PORT, MultisliceIncomplete, multislice_env,
        )
        try:
            return multislice_env(
                self._clients.compute_domain_cliques, self._config.cd_uid,
                self._num_slices, self.clique_id)
        except MultisliceIncomplete:
            return {"MEGASCALE_NUM_SLICES": str(self._num_slices),
                    "MEGASCALE_PORT": str(MEGASCALE_PORT)}

    # ------------------------------------------------------------------
    # readiness (the `compute-domain-daemon check` probe)
    # ------------------------------------------------------------------

    def check(self) -> bool:
        """Ready iff: no fabric error, we are in the clique, and every
        clique member is present in our hosts mapping (all peers
        resolvable — the nvidia-imex-ctl quorum-query analog)."""
        with self._mu:
            if self._fabric_error is not None:
                return False
        cq = self.membership.get()
        if cq is None:
            return False
        mine = cq.daemon_for(self._config.node_name)
        if mine is None or mine.index < 0:
            return False
        from tpu_dra_driver.computedomain.daemon.dnsnames import parse_block
        mapping = parse_block(self._config.hosts_file)
        return all(d.index in mapping for d in cq.daemons)

    # ------------------------------------------------------------------
    # fabric health
    # ------------------------------------------------------------------

    def _on_health(self, event: HealthEvent) -> None:
        if event.kind != HealthEventKind.ICI_LINK_ERROR:
            return
        with self._mu:
            self._fabric_error = event
        log.error("ICI fabric error on %s: %s", event.chip_uuid, event.message)
        # demote ourselves so the controller stops releasing workloads here
        from tpu_dra_driver.api.types import STATUS_NOT_READY
        self.membership.set_status(STATUS_NOT_READY)
        if self._config.gates.enabled(fg.CRASH_ON_ICI_FABRIC_ERRORS):
            # reference CrashOnNVLinkFabricErrors: die so k8s restarts the
            # pod and the clique re-forms on healthy fabric. The health
            # callback runs on the publisher's thread, so signal the main
            # loop (which exits nonzero) instead of raising here.
            self.fatal.set()
            if self._on_fabric_error_cb is not None:
                self._on_fabric_error_cb(event)
