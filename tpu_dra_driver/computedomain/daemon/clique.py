"""ComputeDomainClique membership: join, stable gap-filled index, leave.

Reference analog: cmd/compute-domain-daemon/cdclique.go — cliques are
named ``<cdUID>.<cliqueID>``; each daemon joins the clique for *its* clique
id (for TPUs: the physical ICI slice id from the device library — fabric
reachability is wiring, not choice) and allocates the smallest unused
``Index`` (gap-filling, cdclique.go:350-371) so indices stay stable and
dense as daemons come and go — the index *is* the TPU worker id, so
stability matters: a restarted daemon on the same node must get its old
index back (by nodeName match) rather than a fresh one.
"""

from __future__ import annotations

import logging
from typing import Optional

from tpu_dra_driver.api.types import (
    CliqueDaemon,
    ComputeDomainClique,
    STATUS_NOT_READY,
    STATUS_READY,
)
from tpu_dra_driver.computedomain import DRIVER_NAMESPACE
from tpu_dra_driver.kube.client import ABORT, ResourceClient
from tpu_dra_driver.kube.errors import AlreadyExistsError, NotFoundError
from tpu_dra_driver.pkg import faultinject as fi

log = logging.getLogger(__name__)

fi.register("daemon.clique.join",
            "the clique join/re-join write (fail = daemon boot dies "
            "mid-rendezvous; the DS runner/kubelet restarts the pod and "
            "the clique must re-form with stable indices)")


def gap_filled_index(existing: list[int]) -> int:
    """Smallest non-negative integer not in ``existing``."""
    used = set(existing)
    i = 0
    while i in used:
        i += 1
    return i


class CliqueMembership:
    def __init__(self, cliques: ResourceClient, cd_uid: str, clique_id: str,
                 node_name: str, ip_address: str):
        self._cliques = cliques
        self._cd_uid = cd_uid
        self._clique_id = clique_id
        self._node = node_name
        self._ip = ip_address
        self.name = ComputeDomainClique.clique_name(cd_uid, clique_id)

    # ------------------------------------------------------------------

    def ensure_clique_exists(self) -> None:
        try:
            self._cliques.create(
                ComputeDomainClique.from_obj({
                    "metadata": {"name": self.name,
                                 "namespace": DRIVER_NAMESPACE},
                }).to_obj())
        except AlreadyExistsError:
            pass

    def join(self) -> int:
        """Join (or re-join) the clique; returns the stable index."""
        fi.fire("daemon.clique.join", payload=self.name)
        self.ensure_clique_exists()
        result: dict = {}

        def mutate(obj):
            cq = ComputeDomainClique.from_obj(obj)
            mine = cq.daemon_for(self._node)
            if mine is not None:
                # restarted daemon on the same node: keep the index, refresh IP
                if mine.ip_address == self._ip:
                    result["index"] = mine.index
                    return ABORT
                mine.ip_address = self._ip
                mine.status = STATUS_NOT_READY
                result["index"] = mine.index
            else:
                idx = gap_filled_index([d.index for d in cq.daemons])
                cq.daemons.append(CliqueDaemon(
                    node_name=self._node, ip_address=self._ip,
                    index=idx, status=STATUS_NOT_READY))
                result["index"] = idx
            rendered = cq.to_obj()
            rendered["metadata"] = obj["metadata"]
            return rendered

        self._cliques.retry_update(self.name, DRIVER_NAMESPACE, mutate)
        idx = result["index"]
        log.info("joined clique %s as index %d (node %s, ip %s)",
                 self.name, idx, self._node, self._ip)
        return idx

    def set_status(self, status: str) -> None:
        def mutate(obj):
            cq = ComputeDomainClique.from_obj(obj)
            mine = cq.daemon_for(self._node)
            if mine is None or mine.status == status:
                return ABORT
            mine.status = status
            rendered = cq.to_obj()
            rendered["metadata"] = obj["metadata"]
            return rendered
        try:
            self._cliques.retry_update(self.name, DRIVER_NAMESPACE, mutate)
        except NotFoundError:
            pass

    def set_ready(self) -> None:
        self.set_status(STATUS_READY)

    def leave(self) -> None:
        """Remove our entry (by node + ip, reference cdclique.go:374-404
        removes by pod IP so a *replacement* daemon's fresh entry survives a
        late-running old pod's shutdown)."""
        def mutate(obj):
            cq = ComputeDomainClique.from_obj(obj)
            mine = cq.daemon_for(self._node)
            if mine is None or mine.ip_address != self._ip:
                return ABORT
            cq.daemons = [d for d in cq.daemons if d.node_name != self._node]
            rendered = cq.to_obj()
            rendered["metadata"] = obj["metadata"]
            return rendered
        try:
            self._cliques.retry_update(self.name, DRIVER_NAMESPACE, mutate)
        except NotFoundError:
            pass

    def get(self) -> Optional[ComputeDomainClique]:
        try:
            return ComputeDomainClique.from_obj(
                self._cliques.get(self.name, DRIVER_NAMESPACE))
        except NotFoundError:
            return None
