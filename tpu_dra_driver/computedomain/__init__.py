"""computedomain — the multi-host ICI slice control plane.

Reference analog: the ComputeDomain subsystem (cmd/compute-domain-controller,
cmd/compute-domain-daemon, cmd/compute-domain-kubelet-plugin) that
orchestrates Multi-Node NVLink via IMEX daemons and channels.

TPU redesign (SURVEY.md §2.6/§3.3): ICI needs **no userspace broker** —
libtpu drives the fabric directly given consistent worker identity env.
The control plane's job reduces to the *rendezvous*:

1. controller stamps a per-CD DaemonSet + ResourceClaimTemplates,
2. per-node daemons join a ComputeDomainClique CR (clique id = physical
   ICI slice id), receive stable gap-filled worker indices, and publish
   hostname mappings,
3. the CD kubelet plugin gates workload Prepare on all-nodes-Ready and
   injects ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` / topology env +
   the claim's ICI channel device.

The event flow (label → daemon → ready → workload release) is kept
exactly as the reference's, including the retry envelope semantics —
that ordering is deadlock-free and battle-tested.
"""

# well-known label/finalizer keys
COMPUTE_DOMAIN_LABEL_KEY = "resource.tpu.google.com/computeDomain"
COMPUTE_DOMAIN_FINALIZER = "resource.tpu.google.com/computedomain-protection"
DRIVER_NAMESPACE = "tpu-dra-driver"
