"""CD plugin device state: checkpointed channel/daemon Prepare.

Reference analog: cmd/compute-domain-kubelet-plugin/{device_state.go,
computedomain.go} — the rendezvous-critical half of the driver:

- **channel claims** (workload pods):
  1. strict-decode ComputeDomainChannelConfig (bad config → permanent),
  2. cross-namespace guard: the CD referenced by ``domainID`` must live in
     the claim's namespace (permanent, device_state.go:491-493),
  3. ``AddNodeLabel(node, cdUID)`` — this *triggers* the controller's
     DaemonSet to land a daemon on this node (computedomain.go:312-338),
  4. ``assert_compute_domain_ready``: this node must appear Ready in
     ``CD.status.nodes`` — until then a **transient** error keeps kubelet
     retrying while the daemon rendezvouses (computedomain.go:238-294),
  5. inject the channel device node + worker identity env
     (``TPU_WORKER_ID`` = this node's clique index, ``TPU_WORKER_HOSTNAMES``,
     topology) — the moment of workload release.

- **daemon claims** (the CD daemon pods): cross-ns guard against the
  driver namespace, then inject the daemon runtime env + state dir mount
  (device_state.go:516-573's config-mount analog).

Checkpointing and the channel-overlap guard (channel-0 uniqueness,
device_state.go:635-674) reuse the same machinery as the TPU plugin.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from tpu_dra_driver import COMPUTE_DOMAIN_DRIVER_NAME
from tpu_dra_driver.api.configs import (
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
)
from tpu_dra_driver.api.decoder import STRICT_DECODER, DecodeError
from tpu_dra_driver.api.types import ComputeDomain, ComputeDomainClique, STATUS_READY
from tpu_dra_driver.cdi.generator import CdiDevice, CdiHandler, ContainerEdits
from tpu_dra_driver.computedomain import COMPUTE_DOMAIN_LABEL_KEY, DRIVER_NAMESPACE
from tpu_dra_driver.computedomain.daemon.dnsnames import worker_name
from tpu_dra_driver.computedomain.plugin.devices import (
    DAEMON_DEVICE_NAME,
    NUM_CHANNELS,
    channel_devfs_path,
    parse_channel_name,
)
from tpu_dra_driver.kube.client import ABORT, ClientSets
from tpu_dra_driver.kube.errors import NotFoundError
from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.pkg import tracing
from tpu_dra_driver.pkg.flock import Flock, FlockOptions
from tpu_dra_driver.plugin.checkpoint import (
    Checkpoint,
    CheckpointManager,
    ClaimEntry,
    PreparedDevice,
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    backfill_pools,
)
from tpu_dra_driver.plugin.claims import (
    ClaimInfo,
    config_for_result,
    resolve_opaque_configs,
)
from tpu_dra_driver.plugin.device_state import PermanentError
from tpu_dra_driver.tpulib.interface import TpuLib

log = logging.getLogger(__name__)

fi.register("cd.prepare.after_write_ahead",
            "between the CD claim's PrepareStarted write-ahead and the "
            "CDI spec write (crash = write-ahead persisted, no CDI spec; "
            "restart re-prepares idempotently)")
fi.register("cd.prepare.before_commit",
            "between the CDI spec write and the PrepareCompleted commit "
            "(crash = CDI spec on disk but checkpoint says started)")


class RetryableError(Exception):
    """Transient prepare failure — kubelet/the retry envelope should retry
    (most prominently: the CD not yet Ready on this node)."""


@dataclass
class CdPluginConfig:
    node_name: str
    state_dir: str
    hosts_file_dir: str = "/run/tpu-dra"


class CdDeviceState:
    def __init__(self, clients: ClientSets, lib: TpuLib, cdi: CdiHandler,
                 config: CdPluginConfig,
                 cd_lister=None, clique_lister=None):
        self._clients = clients
        self._lib = lib
        self._cdi = cdi
        self._config = config
        # Informer-backed listers (kube.informer.Informer): readiness
        # checks read the local store instead of LISTing the API on every
        # prepare attempt. Falls back to live reads until the informer is
        # synced (or when constructed without one, e.g. unit tests).
        self._cd_lister = cd_lister
        self._clique_lister = clique_lister
        self._mu = threading.RLock()
        self._cp_mgr = CheckpointManager(config.state_dir)
        self._cp_lock_path = os.path.join(config.state_dir, "cp.lock")
        self._cp_mgr.ensure_exists()
        # Claim uids already PREPARE_COMPLETED, mirrored in memory so the
        # retry envelope can tell "idempotent re-Prepare" (go straight to
        # the checkpoint) from "still converging" (gate on precheck, no
        # checkpoint IO) without a per-attempt flock + read. Seeded from
        # disk once; prepare/unprepare keep it current.
        with self._cp_locked():
            cp = self._cp_mgr.read_or_quarantine()
        self._completed = {uid for uid, e in cp.claims.items()
                           if e.state == PREPARE_COMPLETED}

    def _cp_locked(self):
        return Flock(self._cp_lock_path, FlockOptions(timeout=10.0))

    def get_checkpoint(self) -> Checkpoint:
        with self._cp_locked():
            return self._cp_mgr.read_or_quarantine()

    def precheck(self, claim: ClaimInfo) -> None:
        """Run the readiness gates alone — informer-store reads plus the
        idempotent node label, NO flock/checkpoint IO. Raises
        RetryableError/PermanentError exactly like :meth:`prepare`.

        The retry envelope calls this per attempt so the blocked path
        ("CD not Ready yet") costs microseconds; the flock + checkpoint
        read/writes are paid once, by the final :meth:`prepare`, after the
        gates pass. prepare() still re-validates everything internally, so
        a regression between precheck and prepare stays safe."""
        self._prepare_devices(claim)

    def likely_completed(self, claim_uid: str) -> bool:
        """True when this claim already prepared on this node (in-memory
        mirror of the checkpoint — no IO)."""
        with self._mu:
            return claim_uid in self._completed

    # ------------------------------------------------------------------

    def prepare(self, claim: ClaimInfo) -> List[PreparedDevice]:
        with self._mu, self._cp_locked():
            cp = self._cp_mgr.read_or_quarantine()
            entry = cp.claims.get(claim.uid)
            if entry is not None and entry.state == PREPARE_COMPLETED:
                backfill_pools(entry, claim)
                return entry.prepared_devices
            self._validate_no_overlap(cp, claim)
            # Readiness gates + device/env derivation first: they are pure
            # reads (informer stores, fake lib) plus the idempotent node
            # label, with NO node-local mutation — so the retry-heavy "CD
            # not Ready yet" path must run BEFORE the write-ahead. Event-
            # triggered retries can attempt once per watch event, and the
            # old order paid 2 fsync'd checkpoint writes (write-ahead +
            # rollback) per failed attempt, dominating rendezvous latency.
            prepared, cdi_devices, extra = self._prepare_devices(claim)
            # The write-ahead still covers the only mutation: the CDI
            # claim-spec write below (crash after it -> restart sees
            # PREPARE_STARTED and re-prepares/cleans up as before).
            cp.claims[claim.uid] = ClaimEntry(
                claim_uid=claim.uid, claim_name=claim.name,
                namespace=claim.namespace, state=PREPARE_STARTED)
            with tracing.span("cd.write_ahead"):
                self._cp_mgr.write(cp)
            fi.fire("cd.prepare.after_write_ahead")
            with tracing.span("cd.cdi_write",
                              attributes={"claim": claim.canonical}):
                qualified = self._cdi.write_claim_spec(
                    claim.uid, cdi_devices, extra_common=extra)
            for dev, qname in zip(prepared, qualified):
                dev.cdi_device_ids = [qname]
            cp.claims[claim.uid] = ClaimEntry(
                claim_uid=claim.uid, claim_name=claim.name,
                namespace=claim.namespace, state=PREPARE_COMPLETED,
                prepared_devices=prepared)
            fi.fire("cd.prepare.before_commit")
            with tracing.span("cd.commit"):
                self._cp_mgr.write(cp)
            self._completed.add(claim.uid)
            return prepared

    def unprepare(self, claim_uid: str) -> None:
        with self._mu, self._cp_locked():
            cp = self._cp_mgr.read_or_quarantine()
            self._completed.discard(claim_uid)
            if claim_uid not in cp.claims:
                return
            self._cdi.delete_claim_spec(claim_uid)
            del cp.claims[claim_uid]
            self._cp_mgr.write(cp)

    def _validate_no_overlap(self, cp: Checkpoint, claim: ClaimInfo) -> None:
        """Channel devices are exclusive per node (channel-0 uniqueness:
        two workload claims must not share a channel; use distinct channel
        ids or a single shared claim)."""
        owners = cp.prepared_device_owners()
        for r in claim.results:
            owner = owners.get(r.device)
            if owner is not None and owner != claim.uid:
                raise PermanentError(
                    f"channel device {r.device} already prepared for claim "
                    f"{owner} on this node"
                )

    # ------------------------------------------------------------------

    def _prepare_devices(self, claim: ClaimInfo):
        try:
            configs = resolve_opaque_configs(
                claim, STRICT_DECODER, driver_name=COMPUTE_DOMAIN_DRIVER_NAME)
        except DecodeError as e:
            raise PermanentError(f"bad opaque config: {e}") from e
        if not claim.results:
            raise PermanentError(
                f"claim {claim.canonical} has no allocation results for "
                f"{COMPUTE_DOMAIN_DRIVER_NAME}")

        prepared: List[PreparedDevice] = []
        cdi_devices: List[CdiDevice] = []
        extra = ContainerEdits()
        for result in claim.results:
            rc = config_for_result(configs, result)
            cfg = rc.config if rc else None
            if result.device == DAEMON_DEVICE_NAME:
                if not isinstance(cfg, ComputeDomainDaemonConfig):
                    raise PermanentError(
                        "daemon device requires a ComputeDomainDaemonConfig")
                pd, cd, ex = self._prepare_daemon(claim, result.request, cfg)
            else:
                if not isinstance(cfg, ComputeDomainChannelConfig):
                    raise PermanentError(
                        "channel device requires a ComputeDomainChannelConfig")
                pd, cd, ex = self._prepare_channel(claim, result.request,
                                                   result.device, cfg)
            pd.pool = result.pool
            prepared.append(pd)
            cdi_devices.append(cd)
            extra = extra.merge(ex)
        return prepared, cdi_devices, extra

    # ------------------------------------------------------------------
    # channel path (the workload-release gate)
    # ------------------------------------------------------------------

    def _prepare_channel(self, claim: ClaimInfo, request: str,
                         device: str, cfg: ComputeDomainChannelConfig):
        try:
            chan_id = parse_channel_name(device)
        except ValueError as e:
            raise PermanentError(str(e)) from e
        cd = self._get_compute_domain(cfg.domain_id)
        if cd is None:
            raise RetryableError(
                f"ComputeDomain {cfg.domain_id} not found (yet)")
        if cd.metadata.namespace != claim.namespace:
            raise PermanentError(
                f"claim namespace {claim.namespace!r} does not match "
                f"ComputeDomain namespace {cd.metadata.namespace!r}")
        self._add_node_label(cfg.domain_id)
        node_status = self._assert_compute_domain_ready(cd)
        worker_id, addresses, dns_names = self._worker_identity(cd, node_status)

        env = {
            "TPU_WORKER_ID": str(worker_id),
            # worker addresses must resolve *inside the workload container*,
            # so inject the IPs directly (libtpu accepts IPs here); the
            # stable DNS names + hosts mapping ride along for tooling that
            # wants them (mounted at /etc/tpu-dra/hosts)
            "TPU_WORKER_HOSTNAMES": ",".join(addresses),
            "TPU_WORKER_DNS_NAMES": ",".join(dns_names),
            "TPU_ICI_CHANNEL": str(chan_id),
            "TPU_COMPUTE_DOMAIN": cd.metadata.uid,
        }
        topo = self._lib.host_topology()
        env["TPU_ACCELERATOR_TYPE"] = topo.accelerator_type
        env["TPU_TOPOLOGY"] = topo.topology_string
        if cd.spec.num_slices > 1:
            env.update(self._multislice_env(cd, node_status))

        # allocationMode=All: the claim still holds exactly one DRA channel
        # device, but every channel device node is injected (reference
        # device_state.go:472-476,508-511).
        if cfg.allocation_mode == "All":
            device_nodes = [{"path": channel_devfs_path(i)}
                            for i in range(NUM_CHANNELS)]
        else:
            device_nodes = [{"path": channel_devfs_path(chan_id)}]
        edits = ContainerEdits(
            env=env,
            device_nodes=device_nodes,
            mounts=[{
                # the daemon scopes its files per CD UID under the
                # node-shared hostPath run dir (cmd/compute_domain_daemon
                # cd_run_dir) so co-located domains never cross-read
                "hostPath": os.path.join(self._config.hosts_file_dir,
                                         cd.metadata.uid, "hosts"),
                "containerPath": "/etc/tpu-dra/hosts",
                "options": ["ro", "bind"],
            }],
        )
        name = self._cdi.claim_device_name(claim.uid, device)
        pd = PreparedDevice(canonical_name=device, request=request,
                            device_type="channel",
                            devfs_path=channel_devfs_path(chan_id))
        return pd, CdiDevice(name=name, edits=edits), ContainerEdits()

    def _get_compute_domain(self, domain_uid: str) -> Optional[ComputeDomain]:
        if self._cd_lister is not None and self._cd_lister.synced:
            objs = self._cd_lister.by_index("uid", domain_uid)
            return ComputeDomain.from_obj(objs[0]) if objs else None
        for obj in self._clients.compute_domains.list():
            if obj["metadata"].get("uid") == domain_uid:
                return ComputeDomain.from_obj(obj)
        return None

    def _get_clique_obj(self, clique_name: str):
        """One clique by name — from the informer store when synced
        (zero API round-trips on the retry-heavy readiness path), else
        live. Returns None when absent."""
        if self._clique_lister is not None and self._clique_lister.synced:
            return self._clique_lister.get(clique_name, DRIVER_NAMESPACE)
        try:
            return self._clients.compute_domain_cliques.get(
                clique_name, DRIVER_NAMESPACE)
        except NotFoundError:
            return None

    def _add_node_label(self, cd_uid: str) -> None:
        """Label this node so the controller's DaemonSet schedules a daemon
        here (reference computedomain.go:312-338)."""
        def mutate(obj):
            labels = obj["metadata"].setdefault("labels", {})
            if labels.get(COMPUTE_DOMAIN_LABEL_KEY) == cd_uid:
                return ABORT
            labels[COMPUTE_DOMAIN_LABEL_KEY] = cd_uid
        try:
            self._clients.nodes.retry_update(self._config.node_name, "", mutate)
        except NotFoundError:
            raise RetryableError(
                f"node {self._config.node_name} not registered yet")

    def _assert_compute_domain_ready(self, cd: ComputeDomain):
        """Transient failure until the daemon on *this* node is Ready
        (reference computedomain.go:238-294). Workload pods sit in
        ContainerCreating while kubelet retries."""
        for n in cd.status.nodes:
            if n.name == self._config.node_name and n.status == STATUS_READY:
                return n
        raise RetryableError(
            f"ComputeDomain {cd.metadata.namespace}/{cd.metadata.name}: "
            f"node {self._config.node_name} not Ready yet "
            f"(status={cd.status.status}, "
            f"nodes={[f'{n.name}:{n.status}' for n in cd.status.nodes]})")

    def _worker_identity(self, cd: ComputeDomain,
                         node_status) -> Tuple[int, List[str], List[str]]:
        """worker id = this node's clique index; addresses = members' IPs
        ordered by index (resolvable anywhere); dns_names = the stable
        names backing the hosts-file mapping."""
        clique_name = ComputeDomainClique.clique_name(
            cd.metadata.uid, node_status.clique_id)
        cq_obj = self._get_clique_obj(clique_name)
        if cq_obj is None:
            raise RetryableError(f"clique {clique_name} not found (yet)")
        cq = ComputeDomainClique.from_obj(cq_obj)
        members = sorted((d for d in cq.daemons if d.index >= 0),
                         key=lambda d: d.index)
        # The workload must see the COMPLETE world: releasing with fewer
        # members than expected would start a distributed job with the
        # wrong world size. Transient until everyone has joined. For a
        # multislice CD the per-clique world is numNodes/numSlices (the
        # TPU_WORKER_* identity is slice-local; MEGASCALE_* spans slices).
        expected = cd.spec.num_nodes // max(1, cd.spec.num_slices)
        if len(members) < expected:
            raise RetryableError(
                f"clique {clique_name}: {len(members)}/{expected} "
                f"daemons joined")
        return (node_status.index,
                [d.ip_address for d in members],
                [worker_name(d.index) for d in members])

    def _multislice_env(self, cd: ComputeDomain, node_status) -> Dict[str, str]:
        """MEGASCALE_* DCN bootstrap env (shared derivation:
        computedomain.multislice). Transient until every slice has a live
        clique and the coordinator has joined — releasing earlier would
        boot megascale with a wrong or unreachable world."""
        from tpu_dra_driver.computedomain.multislice import (
            MultisliceIncomplete, multislice_env,
        )
        cliques = (self._clique_lister
                   if (self._clique_lister is not None
                       and self._clique_lister.synced)
                   else self._clients.compute_domain_cliques)
        try:
            return multislice_env(
                cliques, cd.metadata.uid,
                cd.spec.num_slices, node_status.clique_id)
        except MultisliceIncomplete as e:
            raise RetryableError(
                f"multislice {cd.metadata.name}: {e}") from e

    # ------------------------------------------------------------------
    # daemon path
    # ------------------------------------------------------------------

    def _prepare_daemon(self, claim: ClaimInfo, request: str,
                        cfg: ComputeDomainDaemonConfig):
        if claim.namespace != DRIVER_NAMESPACE:
            raise PermanentError(
                f"daemon claims must live in {DRIVER_NAMESPACE!r}, "
                f"got {claim.namespace!r}")
        cd = self._get_compute_domain(cfg.domain_id)
        if cd is None:
            raise RetryableError(f"ComputeDomain {cfg.domain_id} not found (yet)")
        env = {
            "CD_UID": cd.metadata.uid,
            "CD_NAME": cd.metadata.name,
            "CD_NAMESPACE": cd.metadata.namespace,
            "NODE_NAME": self._config.node_name,
        }
        edits = ContainerEdits(
            env=env,
            mounts=[{
                "hostPath": self._config.hosts_file_dir,
                "containerPath": "/run/tpu-dra",
                "options": ["rw", "bind"],
            }],
        )
        name = self._cdi.claim_device_name(claim.uid, DAEMON_DEVICE_NAME)
        pd = PreparedDevice(canonical_name=DAEMON_DEVICE_NAME, request=request,
                            device_type="daemon")
        return pd, CdiDevice(name=name, edits=edits), ContainerEdits()
