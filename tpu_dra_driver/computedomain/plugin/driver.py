"""The compute-domain kubelet plugin driver: the retry envelope.

Reference analog: cmd/compute-domain-kubelet-plugin/driver.go:40-62,
164-232 — unlike the TPU/GPU plugin (one attempt per kubelet call), every
CD claim prepare runs inside an internal retry loop with exponential
backoff under a **45 s budget**, distinguishing permanent errors (no
retry; surfaced immediately) from transient ones (most importantly "CD not
Ready on this node yet", which resolves as the daemon rendezvous
completes). Kubelet itself re-calls Prepare for anything that exhausts the
budget, so workload pods sit in ContainerCreating until release.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from tpu_dra_driver import COMPUTE_DOMAIN_DRIVER_NAME
from tpu_dra_driver.cdi.generator import CdiHandler
from tpu_dra_driver.computedomain import DRIVER_NAMESPACE
from tpu_dra_driver.computedomain.plugin.device_state import (
    CdDeviceState,
    CdPluginConfig,
    RetryableError,
)
from tpu_dra_driver.computedomain.plugin.devices import build_cd_resource_slice
from tpu_dra_driver.kube.client import ClientSets
from tpu_dra_driver.kube.errors import AlreadyExistsError
from tpu_dra_driver.kube.events import (
    EventRecorder,
    emit_claim_event,
    normalize_claim_refs,
)
from tpu_dra_driver.kube.informer import Informer
from tpu_dra_driver.pkg import tracing
from tpu_dra_driver.pkg.workqueue import prep_unprep_rate_limiter
from tpu_dra_driver.plugin.claims import ClaimInfo
from tpu_dra_driver.plugin.device_state import PermanentError
from tpu_dra_driver.plugin.driver import PrepareResult

log = logging.getLogger(__name__)

PREPARE_BUDGET = 45.0  # seconds (reference driver.go:40-46)

#: Never-set event used for the short burst-coalescing pause after a wake
#: (an interruptible bounded wait, not a fixed-interval poll — which is
#: why the reconcile paths ban time.sleep).
_PAUSE = threading.Event()


@dataclass
class CdKubeletPluginConfig:
    node_name: str
    state_dir: str
    cdi_root: str
    hosts_file_dir: str = "/run/tpu-dra"
    prepare_budget: float = PREPARE_BUDGET
    # False restores the fixed-backoff retry envelope (no informer wake on
    # CD/clique transitions) — the poll arm of bench.py's rendezvous
    # benchmark; production always runs event-driven.
    wake_on_events: bool = True


class CdKubeletPlugin:
    def __init__(self, clients: ClientSets, lib, config: CdKubeletPluginConfig):
        self._clients = clients
        self._lib = lib
        self._config = config
        cdi = CdiHandler(cdi_root=config.cdi_root,
                         driver_version=lib.driver_version(),
                         vendor=COMPUTE_DOMAIN_DRIVER_NAME)
        # Informer-backed view of the rendezvous state: CD status
        # transitions and clique membership stream in as watch events; a
        # blocked Prepare re-checks the moment anything changes instead of
        # sleeping out a fixed backoff, and the readiness checks read the
        # local stores instead of LISTing the API per attempt.
        self._cd_informer = Informer(
            clients.compute_domains,
            indexers={"uid": lambda o: (
                ((o.get("metadata") or {}).get("uid"),)
                if (o.get("metadata") or {}).get("uid") else ())})
        self._clique_informer = Informer(clients.compute_domain_cliques,
                                         namespace=DRIVER_NAMESPACE)
        # One wake Event per in-flight prepare (registered below): a
        # single shared event would let one claim's clear() eat a wake
        # another blocked claim had not consumed yet.
        self._waiters: set = set()
        self._waiters_mu = threading.Lock()
        self.state = CdDeviceState(clients, lib, cdi, CdPluginConfig(
            node_name=config.node_name, state_dir=config.state_dir,
            hosts_file_dir=config.hosts_file_dir),
            cd_lister=self._cd_informer,
            clique_lister=self._clique_informer)
        self._events = EventRecorder(
            clients.events, component="compute-domain-kubelet-plugin",
            host=config.node_name)

    @property
    def event_recorder(self) -> EventRecorder:
        """The plugin's Event sink — shared with the SLO engine so
        SLOBurnRate Warnings ride the same deduped async pipeline."""
        return self._events

    def _notify_waiters(self) -> None:
        with self._waiters_mu:
            for ev in self._waiters:
                ev.set()

    def start(self) -> None:
        wake = self._notify_waiters
        self._cd_informer.add_handlers(
            on_add=lambda o: wake(),
            on_update=lambda old, new: wake(),
            on_delete=lambda o: wake())
        self._clique_informer.add_handlers(
            on_add=lambda o: wake(),
            on_update=lambda old, new: wake(),
            on_delete=lambda o: wake())
        self._cd_informer.start()
        self._clique_informer.start()
        self._cd_informer.wait_synced()
        self._clique_informer.wait_synced()
        slice_obj = build_cd_resource_slice(self._config.node_name,
                                            self._lib.slice_id())
        try:
            self._clients.resource_slices.create(slice_obj)
        except AlreadyExistsError:
            existing = self._clients.resource_slices.get(
                slice_obj["metadata"]["name"])
            existing["spec"] = slice_obj["spec"]
            self._clients.resource_slices.update(existing)
        log.info("cd-kubelet-plugin started on %s (clique %s)",
                 self._config.node_name, self._lib.slice_id())

    def shutdown(self) -> None:
        self._cd_informer.stop()
        self._clique_informer.stop()
        self._events.stop(timeout=2.0)

    def healthy(self) -> bool:
        """gRPC healthcheck analog (reference health.go:121-149): verify
        the fabric metadata still answers and the checkpoint is readable."""
        try:
            self._lib.slice_id()
            self.state.get_checkpoint()
            return True
        except Exception:  # chaos-ok: health probe converts to NOT_SERVING
            log.exception("healthcheck failed")
            return False

    # ------------------------------------------------------------------

    def prepare_resource_claims(self, claims: List[Dict]) -> Dict[str, PrepareResult]:
        out: Dict[str, PrepareResult] = {}
        for obj in claims:
            info = ClaimInfo.from_obj(obj, driver_name=COMPUTE_DOMAIN_DRIVER_NAME)
            # cross-process trace pickup: the allocator's root span rides
            # the claim annotation; the whole retry envelope (including
            # the CD-ready rendezvous wait) nests under it
            span = tracing.start_span(
                "cd.prepare", parent=tracing.from_object(obj),
                attributes={"claim": info.canonical,
                            "node": self._config.node_name})
            with tracing.use_span(span):
                res = self._prepare_with_retry(info)
            span.set_attribute("result",
                               "ok" if res.error is None else "error")
            span.end(status="ok" if res.error is None else "error")
            emit_claim_event(
                self._events, self._config.node_name,
                {"uid": info.uid, "name": info.name,
                 "namespace": info.namespace},
                "released", error=res.error, permanent=res.permanent)
            out[info.uid] = res
        return out

    def _prepare_with_retry(self, claim: ClaimInfo) -> PrepareResult:
        """Synchronous retry envelope: event-triggered re-checks within
        the 45 s budget. A transient failure (CD not Ready, clique
        incomplete) waits on the informer wake event with the limiter's
        backoff as a CEILING — any CD/clique transition re-checks
        immediately, so release latency tracks the rendezvous instead of
        the backoff ladder. The latest-wins semantics of the reference's
        internal workqueue reduce to a simple loop when each kubelet call
        carries one claim attempt."""
        limiter = prep_unprep_rate_limiter()
        # This call's own wake event; informer handlers set every
        # registered waiter. The poll arm simply never registers, so the
        # wait below degenerates to the plain fixed backoff.
        waiter = threading.Event()
        if self._config.wake_on_events:
            with self._waiters_mu:
                self._waiters.add(waiter)
        try:
            return self._prepare_attempts(claim, limiter, waiter)
        finally:
            if self._config.wake_on_events:
                with self._waiters_mu:
                    self._waiters.discard(waiter)

    def _prepare_attempts(self, claim: ClaimInfo, limiter,
                          waiter: threading.Event) -> PrepareResult:
        deadline = time.monotonic() + self._config.prepare_budget
        attempt = 0
        # Opened at the first transient failure; covers the whole
        # rendezvous wait (retry events ride on it) and ends when the CD
        # releases this node or the budget runs dry — the span that
        # answers "how long did THIS claim wait for CD-ready, and why".
        wait_span = None
        while True:
            attempt += 1
            # Arm before reading cluster state: an event landing during
            # the attempt must not be lost between fail and wait.
            waiter.clear()
            try:
                # An already-completed claim (kubelet re-calling Prepare)
                # goes straight to prepare() and returns its checkpointed
                # result even mid-regression; anything still converging
                # gates on precheck (lister reads only) first, so the
                # blocked "CD not Ready yet" loop never pays flock +
                # checkpoint IO.
                if not self.state.likely_completed(claim.uid):
                    self.state.precheck(claim)
                devices = self.state.prepare(claim)
                if wait_span is not None:
                    wait_span.set_attribute("attempts", attempt)
                    wait_span.end()
                if attempt > 1:
                    log.info("prepare %s succeeded on attempt %d",
                             claim.canonical, attempt)
                return PrepareResult(devices=devices)
            except PermanentError as e:
                if wait_span is not None:
                    wait_span.end(status="error")
                log.error("prepare %s failed permanently: %s", claim.canonical, e)
                return PrepareResult(error=str(e), permanent=True)
            except RetryableError as e:
                if wait_span is None:
                    wait_span = tracing.start_span(
                        "cd.await_ready", parent=tracing.current_span(),
                        attributes={"claim": claim.canonical,
                                    "node": self._config.node_name})
                wait_span.add_event("retry", attempt=attempt,
                                    reason=str(e)[:200])
                delay = limiter.when(claim.uid)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    wait_span.set_attribute("attempts", attempt)
                    wait_span.end(status="error")
                    log.warning("prepare %s: retry budget exhausted after "
                                "%d attempts: %s", claim.canonical, attempt, e)
                    return PrepareResult(error=str(e), permanent=False)
                # The backoff is a ceiling, not guaranteed spend — an
                # event can release the claim any moment — so never
                # forfeit remaining budget just because the ceiling
                # outgrew it: wait the smaller of the two.
                delay = min(delay, remaining)
                log.debug("prepare %s transient (attempt %d, re-check "
                          "within %.2fs): %s",
                          claim.canonical, attempt, delay, e)
                if waiter.wait(timeout=delay):
                    # Batch the burst: rendezvous transitions arrive in
                    # clusters (N joins, N ready flips); a short quiet
                    # window per wake re-checks once per cluster instead
                    # of once per event.
                    _PAUSE.wait(timeout=0.003)
            except Exception as e:  # chaos-ok: surfaced to kubelet, retried
                if wait_span is not None:
                    wait_span.end(status="error")
                log.exception("prepare %s failed", claim.canonical)
                return PrepareResult(error=str(e), permanent=False)

    def unprepare_resource_claims(self, claim_refs: List) -> Dict[str, Optional[str]]:
        """``claim_refs`` entries are bare uid strings or
        ``{"uid", "name", "namespace"}`` dicts (the gRPC layer passes
        full kubelet refs so Events can name the claim)."""
        out: Dict[str, Optional[str]] = {}
        for uid, ref in normalize_claim_refs(claim_refs).items():
            try:
                self.state.unprepare(uid)
                out[uid] = None
            except Exception as e:  # chaos-ok: surfaced to kubelet, retried
                log.exception("unprepare %s failed", uid)
                out[uid] = str(e)
            emit_claim_event(self._events, self._config.node_name, ref,
                             "unprepared", error=out[uid])
        return out
