"""The compute-domain kubelet plugin driver: the retry envelope.

Reference analog: cmd/compute-domain-kubelet-plugin/driver.go:40-62,
164-232 — unlike the TPU/GPU plugin (one attempt per kubelet call), every
CD claim prepare runs inside an internal retry loop with exponential
backoff under a **45 s budget**, distinguishing permanent errors (no
retry; surfaced immediately) from transient ones (most importantly "CD not
Ready on this node yet", which resolves as the daemon rendezvous
completes). Kubelet itself re-calls Prepare for anything that exhausts the
budget, so workload pods sit in ContainerCreating until release.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from tpu_dra_driver import COMPUTE_DOMAIN_DRIVER_NAME
from tpu_dra_driver.cdi.generator import CdiHandler
from tpu_dra_driver.computedomain.plugin.device_state import (
    CdDeviceState,
    CdPluginConfig,
    RetryableError,
)
from tpu_dra_driver.computedomain.plugin.devices import build_cd_resource_slice
from tpu_dra_driver.kube.client import ClientSets
from tpu_dra_driver.kube.errors import AlreadyExistsError
from tpu_dra_driver.pkg.workqueue import prep_unprep_rate_limiter
from tpu_dra_driver.plugin.claims import ClaimInfo
from tpu_dra_driver.plugin.device_state import PermanentError
from tpu_dra_driver.plugin.driver import PrepareResult

log = logging.getLogger(__name__)

PREPARE_BUDGET = 45.0  # seconds (reference driver.go:40-46)


@dataclass
class CdKubeletPluginConfig:
    node_name: str
    state_dir: str
    cdi_root: str
    hosts_file_dir: str = "/run/tpu-dra"
    prepare_budget: float = PREPARE_BUDGET


class CdKubeletPlugin:
    def __init__(self, clients: ClientSets, lib, config: CdKubeletPluginConfig):
        self._clients = clients
        self._lib = lib
        self._config = config
        cdi = CdiHandler(cdi_root=config.cdi_root,
                         driver_version=lib.driver_version(),
                         vendor=COMPUTE_DOMAIN_DRIVER_NAME)
        self.state = CdDeviceState(clients, lib, cdi, CdPluginConfig(
            node_name=config.node_name, state_dir=config.state_dir,
            hosts_file_dir=config.hosts_file_dir))

    def start(self) -> None:
        slice_obj = build_cd_resource_slice(self._config.node_name,
                                            self._lib.slice_id())
        try:
            self._clients.resource_slices.create(slice_obj)
        except AlreadyExistsError:
            existing = self._clients.resource_slices.get(
                slice_obj["metadata"]["name"])
            existing["spec"] = slice_obj["spec"]
            self._clients.resource_slices.update(existing)
        log.info("cd-kubelet-plugin started on %s (clique %s)",
                 self._config.node_name, self._lib.slice_id())

    def healthy(self) -> bool:
        """gRPC healthcheck analog (reference health.go:121-149): verify
        the fabric metadata still answers and the checkpoint is readable."""
        try:
            self._lib.slice_id()
            self.state.get_checkpoint()
            return True
        except Exception:
            log.exception("healthcheck failed")
            return False

    # ------------------------------------------------------------------

    def prepare_resource_claims(self, claims: List[Dict]) -> Dict[str, PrepareResult]:
        out: Dict[str, PrepareResult] = {}
        for obj in claims:
            info = ClaimInfo.from_obj(obj, driver_name=COMPUTE_DOMAIN_DRIVER_NAME)
            out[info.uid] = self._prepare_with_retry(info)
        return out

    def _prepare_with_retry(self, claim: ClaimInfo) -> PrepareResult:
        """Synchronous retry envelope: exponential backoff within the 45 s
        budget; the latest-wins semantics of the reference's internal
        workqueue reduce to a simple loop when each kubelet call carries
        one claim attempt."""
        limiter = prep_unprep_rate_limiter()
        deadline = time.monotonic() + self._config.prepare_budget
        attempt = 0
        while True:
            attempt += 1
            try:
                devices = self.state.prepare(claim)
                if attempt > 1:
                    log.info("prepare %s succeeded on attempt %d",
                             claim.canonical, attempt)
                return PrepareResult(devices=devices)
            except PermanentError as e:
                log.error("prepare %s failed permanently: %s", claim.canonical, e)
                return PrepareResult(error=str(e), permanent=True)
            except RetryableError as e:
                delay = limiter.when(claim.uid)
                if time.monotonic() + delay > deadline:
                    log.warning("prepare %s: retry budget exhausted after "
                                "%d attempts: %s", claim.canonical, attempt, e)
                    return PrepareResult(error=str(e), permanent=False)
                log.debug("prepare %s transient (attempt %d, retry in %.2fs): %s",
                          claim.canonical, attempt, delay, e)
                time.sleep(delay)
            except Exception as e:
                log.exception("prepare %s failed", claim.canonical)
                return PrepareResult(error=str(e), permanent=False)

    def unprepare_resource_claims(self, claim_uids: List[str]) -> Dict[str, Optional[str]]:
        out: Dict[str, Optional[str]] = {}
        for uid in claim_uids:
            try:
                self.state.unprepare(uid)
                out[uid] = None
            except Exception as e:
                log.exception("unprepare %s failed", uid)
                out[uid] = str(e)
        return out
