"""Compute-domain device inventory: ICI channels + the daemon device.

Reference analog: cmd/compute-domain-kubelet-plugin/nvlib.go:160-186,
358-361 — each node advertises 2048 IMEX ``channel`` devices plus one
``daemon`` device under driver ``compute-domain.nvidia.com``.

TPU mapping: a *channel* is a claim-scoped ICI-access grant — preparing it
injects the worker-identity env + the channel device node into the
workload container. The *daemon* device is claimed only by the per-CD
daemon pods the controller stamps.
"""

from __future__ import annotations

from typing import Dict, List

from tpu_dra_driver import COMPUTE_DOMAIN_DRIVER_NAME

NUM_CHANNELS = 2048  # parity with the reference (nvlib.go:358-361)

CHANNEL_DEVFS_DIR = "/dev/tpu-ici-channels"


def channel_name(i: int) -> str:
    return f"channel-{i}"


def channel_devfs_path(i: int) -> str:
    return f"{CHANNEL_DEVFS_DIR}/channel{i}"


def parse_channel_name(name: str) -> int:
    """channel-<i> -> i; raises ValueError otherwise."""
    if not name.startswith("channel-"):
        raise ValueError(f"not a channel device: {name!r}")
    return int(name[len("channel-"):])


DAEMON_DEVICE_NAME = "daemon"


def build_cd_resource_slice(node_name: str, clique_id: str,
                            num_channels: int = NUM_CHANNELS) -> Dict:
    """One slice per node with the daemon device + all channels."""
    devices: List[Dict] = [{
        "name": DAEMON_DEVICE_NAME,
        "attributes": {
            "type": {"string": "daemon"},
            "cliqueID": {"string": clique_id},
        },
        "capacity": {},
    }]
    for i in range(num_channels):
        devices.append({
            "name": channel_name(i),
            "attributes": {
                "type": {"string": "channel"},
                "id": {"int": i},
                "cliqueID": {"string": clique_id},
            },
            "capacity": {},
        })
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceSlice",
        "metadata": {"name": f"{node_name}-{COMPUTE_DOMAIN_DRIVER_NAME}"},
        "spec": {
            "driver": COMPUTE_DOMAIN_DRIVER_NAME,
            "nodeName": node_name,
            "pool": {"name": node_name, "generation": 1,
                     "resourceSliceCount": 1},
            "devices": devices,
        },
    }
