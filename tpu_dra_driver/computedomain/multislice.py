"""Multislice (DCN) bootstrap derivation — the single source of truth.

Both the CD kubelet plugin (authoritative, release-gated, at Prepare) and
the per-node daemon (best-effort worker-env rendering) derive the same
facts from the ComputeDomain's cliques:

- slice ordering: lexicographic over the *live* cliques' ids, so every
  node computes identical slice ids with no extra coordination;
- the coordinator: slice 0's index-0 worker.

"Live" excludes empty cliques: a departed/replaced slice leaves its
clique object behind with no members (``leave()`` removes entries, the
object itself is only deleted at CD teardown), and counting such shells
would wedge the coordinator lookup or shift slice ids.
"""

from __future__ import annotations

from typing import Dict, List

from tpu_dra_driver.api.types import ComputeDomainClique
from tpu_dra_driver.computedomain import DRIVER_NAMESPACE

# DCN rendezvous port the megascale transport listens on.
MEGASCALE_PORT = 8080


class MultisliceIncomplete(Exception):
    """The cross-slice world cannot be derived yet — transient; callers
    gating workload release map this to their retry mechanism."""


def live_cliques(cliques_client, cd_uid: str) -> List[Dict]:
    """The CD's cliques that have at least one indexed member, in slice
    order (lexicographic by clique name)."""
    prefix = f"{cd_uid}."
    out = [o for o in cliques_client.list(namespace=DRIVER_NAMESPACE)
           if o["metadata"]["name"].startswith(prefix)
           and any((d.get("index", -1)) >= 0 for d in o.get("daemons") or [])]
    out.sort(key=lambda o: o["metadata"]["name"])
    return out


def multislice_env(cliques_client, cd_uid: str, num_slices: int,
                   own_clique_id: str) -> Dict[str, str]:
    """MEGASCALE_* env for one worker, or raises MultisliceIncomplete.

    With more live cliques than numSlices (should not persist — the
    controller prunes dead members and empty shells are ignored), the
    first numSlices in slice order are canonical; a node whose clique
    is outside that set is not releasable.
    """
    cliques = live_cliques(cliques_client, cd_uid)
    if len(cliques) < num_slices:
        raise MultisliceIncomplete(
            f"{len(cliques)}/{num_slices} slices have formed cliques")
    prefix = f"{cd_uid}."
    clique_ids = [o["metadata"]["name"][len(prefix):]
                  for o in cliques[:num_slices]]
    if own_clique_id not in clique_ids:
        raise MultisliceIncomplete(
            f"own clique {own_clique_id!r} not among the {num_slices} "
            f"canonical slices {clique_ids}")
    coord = ComputeDomainClique.from_obj(cliques[0])
    c0 = next((d for d in coord.daemons
               if d.index == 0 and d.ip_address), None)
    if c0 is None:
        raise MultisliceIncomplete(
            "coordinator (slice 0 worker 0) not joined yet")
    return {
        "MEGASCALE_NUM_SLICES": str(num_slices),
        "MEGASCALE_SLICE_ID": str(clique_ids.index(own_clique_id)),
        "MEGASCALE_COORDINATOR_ADDRESS": f"{c0.ip_address}:{MEGASCALE_PORT}",
        "MEGASCALE_PORT": str(MEGASCALE_PORT),
    }
