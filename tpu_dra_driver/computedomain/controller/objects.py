"""Stamped child objects: per-CD DaemonSet and ResourceClaimTemplates.

Reference analog: the in-image Go templates
(templates/compute-domain-daemon.tmpl.yaml,
compute-domain-daemon-claim-template.tmpl.yaml,
compute-domain-workload-claim-template.tmpl.yaml) rendered by
daemonset.go:189-251 and resourceclaimtemplate.go:304-399. Like the
reference, the controller renders the template *files* (shipped in-image
under /templates) rather than hand-building dicts, so the documented
contract and the stamped objects cannot drift.
"""

from __future__ import annotations

import os
import re
import string
from typing import Dict

import yaml

from tpu_dra_driver import API_GROUP, API_VERSION, COMPUTE_DOMAIN_DRIVER_NAME
from tpu_dra_driver.api.types import ComputeDomain
from tpu_dra_driver.computedomain import COMPUTE_DOMAIN_LABEL_KEY, DRIVER_NAMESPACE

DAEMON_DEVICE_CLASS = "compute-domain-daemon.tpu.google.com"
DEFAULT_CHANNEL_DEVICE_CLASS = "compute-domain-default-channel.tpu.google.com"

# In-image template location (reference: /templates baked into the
# container, versions.mk; here the repo root's templates/ dir, override
# via env for containerized layouts).
_DEFAULT_TEMPLATES_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..", "templates")

DEFAULT_IMAGE = "tpu-dra-driver:latest"


def templates_dir() -> str:
    return os.environ.get("TPU_DRA_TEMPLATES_DIR",
                          os.path.normpath(_DEFAULT_TEMPLATES_DIR))


class TemplateError(RuntimeError):
    pass


# Textual substitution into YAML means every value must be inert YAML
# scalar content. This allowlist covers all legitimate values (DNS-1123
# names/uids, image refs incl. registries/digests, group/version paths)
# and excludes quotes, whitespace and newlines — the YAML-injection
# characters. User-controlled names that fail this never reach the
# cluster half-rendered; they fail loudly at reconcile. Required values
# must be NON-empty too (an empty IMAGE or CD_UID rendering as "" would
# surface as a confusing downstream API rejection instead of a loud
# TemplateError here); keys whose emptiness legitimately means
# "disabled" are listed explicitly.
_SAFE_VALUE = re.compile(r"^[A-Za-z0-9._:/@\-]+$")
_MAY_BE_EMPTY = frozenset({"DAEMON_HTTP_ENDPOINT"})


# Raw template text cached per path (validated by mtime): reconciles
# re-render on every CD event, and re-reading an unchanged file from disk
# each time put file-IO latency on the rendezvous critical path. The
# mtime check keeps edited templates (tests, live chart tweaks) visible.
_template_cache: Dict[str, tuple] = {}  # path -> (mtime_ns, raw)


def _template_text(path: str) -> str:
    mtime = os.stat(path).st_mtime_ns
    cached = _template_cache.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    with open(path, "r", encoding="utf-8") as fh:
        raw = fh.read()
    _template_cache[path] = (mtime, raw)
    return raw


def render_template(name: str, variables: Dict[str, str]) -> Dict:
    """Substitute ``${VAR}`` placeholders in templates/<name> and parse.

    Strict: an unknown or leftover placeholder raises, and every value
    must match the inert-scalar allowlist (a half-rendered or
    structure-altered manifest applied to a cluster is worse than a
    loud failure)."""
    path = os.path.join(templates_dir(), name)
    raw = _template_text(path)
    for key, val in variables.items():
        if str(val) == "" and key in _MAY_BE_EMPTY:
            continue
        if not _SAFE_VALUE.match(str(val)):
            raise TemplateError(
                f"{name}: value for ${{{key}}} contains characters unsafe "
                f"for YAML substitution (or is empty): {val!r}")
    try:
        rendered = string.Template(raw).substitute(variables)
    except KeyError as exc:
        raise TemplateError(f"{name}: unsubstituted placeholder {exc}") from exc
    except ValueError as exc:   # bare `$` → invalid placeholder syntax
        raise TemplateError(f"{name}: invalid placeholder: {exc}") from exc
    try:
        obj = yaml.safe_load(rendered)
    except yaml.YAMLError as exc:
        raise TemplateError(f"{name}: rendered YAML does not parse: {exc}") from exc
    if not isinstance(obj, dict):
        raise TemplateError(f"{name}: rendered to {type(obj).__name__}, not a mapping")
    return obj


def daemonset_name(cd: ComputeDomain) -> str:
    return f"cd-daemon-{cd.metadata.uid}"


def daemon_rct_name(cd: ComputeDomain) -> str:
    return f"cd-daemon-claim-{cd.metadata.uid}"


def _common_vars(cd: ComputeDomain) -> Dict[str, str]:
    return {
        "CD_UID": cd.metadata.uid,
        "CD_NAME": cd.metadata.name,
        "CD_NAMESPACE": cd.metadata.namespace,
        "DRIVER_NAMESPACE": DRIVER_NAMESPACE,
        "DRIVER_NAME": COMPUTE_DOMAIN_DRIVER_NAME,
        "API_GROUP_VERSION": f"{API_GROUP}/{API_VERSION}",
    }


def build_daemonset(cd: ComputeDomain, image: str = "",
                    log_verbosity: int = 4,
                    device_backend: str = "native",
                    log_format: str = "text",
                    http_endpoint: str = "") -> Dict:
    """The per-CD DaemonSet. Node targeting: only nodes labeled with this
    CD's uid (the CD kubelet plugin adds the label when a workload pod's
    claim first hits the node — reference daemonset.go:206-250).

    No ownerReference: the CD lives in the *user's* namespace and
    Kubernetes forbids cross-namespace owners (the GC would treat the
    owner as absent and delete this DS). Lifecycle is handled by the
    label + finalizer teardown + orphan cleanup, like the reference."""
    # env resolution happens at the flag layer (--driver-image env
    # DRIVER_IMAGE in cmd/compute_domain_controller.py) — no ambient
    # environment reads here
    image = image or DEFAULT_IMAGE
    vars_ = _common_vars(cd)
    vars_.update({
        "IMAGE": image,
        "LOG_VERBOSITY": str(log_verbosity),
        "DEVICE_BACKEND": device_backend,
        "LOG_FORMAT": log_format,
        # "" disables the DebugHTTPServer; non-empty makes the daemon's
        # metrics/traces scrapeable (it runs hostNetwork, so the port
        # must be chosen cluster-wide)
        "DAEMON_HTTP_ENDPOINT": http_endpoint,
    })
    ds = render_template("compute-domain-daemon.tmpl.yaml", vars_)
    assert ds["metadata"]["labels"][COMPUTE_DOMAIN_LABEL_KEY] == cd.metadata.uid
    return ds


def build_daemon_rct(cd: ComputeDomain) -> Dict:
    """ResourceClaimTemplate for the daemon pod's claim: one ``daemon``
    device of the CD driver, carrying the domain id in its opaque config."""
    vars_ = _common_vars(cd)
    vars_["DAEMON_DEVICE_CLASS"] = DAEMON_DEVICE_CLASS
    return render_template("compute-domain-daemon-claim-template.tmpl.yaml",
                           vars_)


def build_workload_rct(cd: ComputeDomain) -> Dict:
    """The workload ResourceClaimTemplate, created under the user-chosen
    name in the CD's namespace (reference resourceclaimtemplate.go:364-399).
    Workload pods reference it; each pod's claim yields one ICI channel
    device whose opaque config ties it back to this domain."""
    vars_ = _common_vars(cd)
    vars_.update({
        "RCT_NAME": cd.spec.channel.resource_claim_template_name,
        "CHANNEL_DEVICE_CLASS": DEFAULT_CHANNEL_DEVICE_CLASS,
        # flows into the opaque ComputeDomainChannelConfig; the claim still
        # allocates exactly one channel device, "All" widens the CDI
        # injection (reference resourceclaimtemplate.go:378)
        "ALLOCATION_MODE": cd.spec.channel.allocation_mode or "Single",
    })
    return render_template("compute-domain-workload-claim-template.tmpl.yaml",
                           vars_)
