"""Stamped child objects: per-CD DaemonSet and ResourceClaimTemplates.

Reference analog: the in-image Go templates
(templates/compute-domain-daemon.tmpl.yaml,
compute-domain-daemon-claim-template.tmpl.yaml,
compute-domain-workload-claim-template.tmpl.yaml) rendered by
daemonset.go:189-251 and resourceclaimtemplate.go:304-399. Here the
objects are built as dicts (the YAML templates in /templates mirror these
shapes for the Helm-deployed production path).
"""

from __future__ import annotations

from typing import Dict

from tpu_dra_driver import API_GROUP, API_VERSION, COMPUTE_DOMAIN_DRIVER_NAME
from tpu_dra_driver.api.types import ComputeDomain
from tpu_dra_driver.computedomain import COMPUTE_DOMAIN_LABEL_KEY, DRIVER_NAMESPACE

DAEMON_DEVICE_CLASS = "compute-domain-daemon.tpu.google.com"
DEFAULT_CHANNEL_DEVICE_CLASS = "compute-domain-default-channel.tpu.google.com"


def daemonset_name(cd: ComputeDomain) -> str:
    return f"cd-daemon-{cd.metadata.uid}"


def daemon_rct_name(cd: ComputeDomain) -> str:
    return f"cd-daemon-claim-{cd.metadata.uid}"


def build_daemonset(cd: ComputeDomain, image: str = "tpu-dra-driver:latest",
                    log_verbosity: int = 4,
                    device_backend: str = "native") -> Dict:
    """The per-CD DaemonSet. Node targeting: only nodes labeled with this
    CD's uid (the CD kubelet plugin adds the label when a workload pod's
    claim first hits the node — reference daemonset.go:206-250)."""
    uid = cd.metadata.uid
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {
            "name": daemonset_name(cd),
            "namespace": DRIVER_NAMESPACE,
            # No ownerReference: the CD lives in the *user's* namespace and
            # Kubernetes forbids cross-namespace owners (the GC would treat
            # the owner as absent and delete this DS). Lifecycle is handled
            # by the label + finalizer teardown + orphan cleanup, exactly
            # like the reference controller.
            "labels": {COMPUTE_DOMAIN_LABEL_KEY: uid},
        },
        "spec": {
            "selector": {"matchLabels": {COMPUTE_DOMAIN_LABEL_KEY: uid}},
            "template": {
                "metadata": {"labels": {COMPUTE_DOMAIN_LABEL_KEY: uid}},
                "spec": {
                    "nodeSelector": {COMPUTE_DOMAIN_LABEL_KEY: uid},
                    "tolerations": [{"operator": "Exists"}],
                    "containers": [{
                        "name": "compute-domain-daemon",
                        "image": image,
                        "command": ["compute-domain-daemon",
                                    f"--compute-domain-uid={uid}",
                                    f"--compute-domain-name={cd.metadata.name}",
                                    f"--compute-domain-namespace={cd.metadata.namespace}",
                                    f"-v={log_verbosity}"],
                        # the daemon must run the same hardware backend as
                        # the plugins (fake on demo clusters)
                        "env": [{"name": "DEVICE_BACKEND",
                                 "value": device_backend}],
                        # exec readiness probe = `compute-domain-daemon check`
                        # (reference main.go:425-451); generous startup budget
                        "startupProbe": {
                            "exec": {"command": ["compute-domain-daemon", "check"]},
                            "periodSeconds": 1, "failureThreshold": 1200,
                        },
                        "readinessProbe": {
                            "exec": {"command": ["compute-domain-daemon", "check"]},
                            "periodSeconds": 5,
                        },
                        "resources": {"claims": [{"name": "cd-daemon"}]},
                    }],
                    "resourceClaims": [{
                        "name": "cd-daemon",
                        "resourceClaimTemplateName": daemon_rct_name(cd),
                    }],
                },
            },
        },
    }


def build_daemon_rct(cd: ComputeDomain) -> Dict:
    """ResourceClaimTemplate for the daemon pod's claim: one ``daemon``
    device of the CD driver, carrying the domain id in its opaque config."""
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaimTemplate",
        "metadata": {
            "name": daemon_rct_name(cd),
            "namespace": DRIVER_NAMESPACE,
            "labels": {COMPUTE_DOMAIN_LABEL_KEY: cd.metadata.uid},
        },
        "spec": {"spec": {"devices": {
            "requests": [{
                "name": "daemon",
                "deviceClassName": DAEMON_DEVICE_CLASS,
                "selectors": [{"attribute": "type", "equals": "daemon"}],
            }],
            "config": [{
                "requests": ["daemon"],
                "opaque": {
                    "driver": COMPUTE_DOMAIN_DRIVER_NAME,
                    "parameters": {
                        "apiVersion": f"{API_GROUP}/{API_VERSION}",
                        "kind": "ComputeDomainDaemonConfig",
                        "domainID": cd.metadata.uid,
                    },
                },
            }],
        }}},
    }


def build_workload_rct(cd: ComputeDomain) -> Dict:
    """The workload ResourceClaimTemplate, created under the user-chosen
    name in the CD's namespace (reference resourceclaimtemplate.go:364-399).
    Workload pods reference it; each pod's claim yields one ICI channel
    device whose opaque config ties it back to this domain."""
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaimTemplate",
        "metadata": {
            "name": cd.spec.channel.resource_claim_template_name,
            "namespace": cd.metadata.namespace,
            "labels": {COMPUTE_DOMAIN_LABEL_KEY: cd.metadata.uid},
        },
        "spec": {"spec": {"devices": {
            "requests": [{
                "name": "channel",
                "deviceClassName": DEFAULT_CHANNEL_DEVICE_CLASS,
                "selectors": [
                    {"attribute": "type", "equals": "channel"},
                    {"attribute": "id", "equals": 0},
                ],
            }],
            "config": [{
                "requests": ["channel"],
                "opaque": {
                    "driver": COMPUTE_DOMAIN_DRIVER_NAME,
                    "parameters": {
                        "apiVersion": f"{API_GROUP}/{API_VERSION}",
                        "kind": "ComputeDomainChannelConfig",
                        "domainID": cd.metadata.uid,
                    },
                },
            }],
        }}},
    }
