"""The compute-domain-controller: ComputeDomain reconciliation.

Reference analog: cmd/compute-domain-controller/{computedomain.go:298-374,
daemonset.go, resourceclaimtemplate.go, cdstatus.go:120-260, node.go,
cleanup.go}. Responsibilities:

- on CD add/update: add finalizer, stamp the per-CD DaemonSet + daemon
  RCT (driver namespace) + workload RCT (user namespace), enforce the
  max-nodes cap;
- **event-driven status sync**: shared pod + clique informers (indexed by
  CD uid, the client-go SharedInformer/lister shape of the reference's
  cdstatus controller) enqueue a debounced per-CD ``status:<uid>`` key on
  the keyed workqueue; each sync copies ComputeDomainClique daemon entries
  into ``CD.status.nodes`` and flips the global status Ready when >=
  numNodes nodes are Ready (pruning stale nodes). A slow periodic pass
  (default 30 s) remains only as a resync backstop for missed events;
- on CD delete: tear down children (DS, RCTs, cliques, node labels), then
  drop the finalizer;
- periodic orphan cleanup: children whose CD no longer exists.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra_driver.api.types import (
    ComputeDomain,
    ComputeDomainClique,
    DEFAULT_MAX_NODES_PER_DOMAIN,
    STATUS_NOT_READY,
    STATUS_READY,
    ComputeDomainNodeStatus,
)
from tpu_dra_driver.computedomain import (
    COMPUTE_DOMAIN_FINALIZER,
    COMPUTE_DOMAIN_LABEL_KEY,
    DRIVER_NAMESPACE,
)
from tpu_dra_driver.computedomain.daemon.daemon import CLIQUE_ID_LABEL_KEY
from tpu_dra_driver.computedomain.controller.objects import (
    build_daemon_rct,
    build_daemonset,
    build_workload_rct,
    daemon_rct_name,
)
from tpu_dra_driver.kube.client import ABORT, ClientSets
from tpu_dra_driver.kube.errors import AlreadyExistsError, ConflictError, NotFoundError
from tpu_dra_driver.kube.events import (
    REASON_CD_READY,
    REASON_VALIDATION_FAILED,
    EventRecorder,
    object_ref,
)
from tpu_dra_driver.kube.informer import Informer
from tpu_dra_driver.pkg import tracing
from tpu_dra_driver.pkg.metrics import DEFAULT_REGISTRY, QueueMetrics, Registry
from tpu_dra_driver.pkg.workqueue import WorkQueue, default_controller_rate_limiter

log = logging.getLogger(__name__)

# The reference cdstatus.go ran a 2 s poll; status sync is now informer
# event-triggered and the interval is only the resync backstop that heals
# a missed watch event.
STATUS_SYNC_INTERVAL = 30.0
# Trailing debounce for per-CD status sync: a burst of daemon joins
# (events landing closer together than this) coalesces into one sync and
# at most one status write.
STATUS_DEBOUNCE = 0.01
ORPHAN_CLEANUP_INTERVAL = 600.0


@dataclass
class ControllerConfig:
    max_nodes_per_domain: int = DEFAULT_MAX_NODES_PER_DOMAIN
    status_sync_interval: float = STATUS_SYNC_INTERVAL
    orphan_cleanup_interval: float = ORPHAN_CLEANUP_INTERVAL
    # Trailing debounce before an event-triggered per-CD status sync runs;
    # every further event for the same CD pushes the deadline back.
    status_debounce: float = STATUS_DEBOUNCE
    # Workqueue workers. >1 lets independent CDs reconcile/status-sync in
    # parallel; per-key latest-wins semantics still serialize meaningfully.
    workers: int = 2
    # False restores the poll-only architecture (full LISTs on every
    # status_sync_interval tick, no event triggers) — kept as the
    # comparison arm for bench.py's rendezvous benchmark.
    event_driven: bool = True
    # Extra namespaces where the driver may manage CD DaemonSets
    # (reference mnsdaemonset.go + --additional-namespaces): a CD's
    # DaemonSet found in any managed namespace is adopted/updated there;
    # new ones are always created in the driver namespace; teardown and
    # orphan cleanup span all managed namespaces.
    additional_namespaces: List[str] = field(default_factory=list)
    # hardware backend the stamped CD daemon pods must use; matches the
    # chart-wide deviceBackend value ("fake" on demo clusters)
    device_backend: str = "native"
    # image + verbosity for stamped CD daemon pods ("" → $DRIVER_IMAGE or
    # the objects.DEFAULT_IMAGE fallback; reference plumbs these through
    # the DaemonSet template, daemonset.go:206-217)
    daemon_image: str = ""
    daemon_log_verbosity: int = 4
    # observability plumbed into stamped CD daemon pods: log format and
    # the daemon's own --http-endpoint ("" keeps it disabled; the daemon
    # runs hostNetwork so the port is a cluster-wide choice)
    daemon_log_format: str = "text"
    daemon_http_endpoint: str = ""


class ComputeDomainController:
    def __init__(self, clients: ClientSets,
                 config: Optional[ControllerConfig] = None,
                 registry: Optional[Registry] = None):
        self._clients = clients
        self._config = config or ControllerConfig()
        self.registry = registry or DEFAULT_REGISTRY
        self._queue = WorkQueue(default_controller_rate_limiter(),
                                name="cd-controller",
                                metrics=QueueMetrics("cd-controller",
                                                     self.registry))
        self._reconciles = self.registry.counter(
            "computedomain_reconciles_total",
            "ComputeDomain reconcile attempts by result", ("result",))
        self._reconcile_duration = self.registry.histogram(
            "computedomain_reconcile_duration_seconds",
            "Wall time of one ComputeDomain reconcile")
        self._status_triggers = self.registry.counter(
            "dra_cd_status_sync_triggers_total",
            "ComputeDomain status syncs by what triggered them",
            ("source",))
        self._status_writes = self.registry.counter(
            "dra_cd_status_writes_total",
            "ComputeDomain status updates actually written (unchanged "
            "syncs abort without an API write)")
        self._rendezvous_seconds = self.registry.histogram(
            "dra_cd_rendezvous_seconds",
            "ComputeDomain rendezvous: first observed daemon join to "
            "status Ready",
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                     5.0, 10.0, 30.0, 60.0))
        # CD uid -> monotonic time the first daemon join was observed while
        # the CD was not Ready (feeds the rendezvous histogram).
        self._rendezvous_t0: Dict[str, float] = {}
        # CD uid -> open ``cd.rendezvous`` span (keyed by the CD's own
        # trace — the traceparent annotation stamped at first reconcile);
        # ended when the Ready flip is written.
        self._rendezvous_spans: Dict[str, object] = {}
        self._events_rec = EventRecorder(
            clients.events, component="compute-domain-controller")

        def pod_cd_uid(obj: Dict):
            uid = ((obj.get("metadata") or {}).get("labels") or {}).get(
                COMPUTE_DOMAIN_LABEL_KEY)
            return (uid,) if uid else ()

        def clique_cd_uid(obj: Dict):
            name = (obj.get("metadata") or {}).get("name", "")
            return (name.split(".", 1)[0],) if name else ()

        self._cd_informer = Informer(
            clients.compute_domains,
            indexers={"uid": lambda o: (
                ((o.get("metadata") or {}).get("uid"),)
                if (o.get("metadata") or {}).get("uid") else ())})
        # One pod informer PER managed namespace (the reference's filtered
        # daemon-pod informers): the store holds daemon-pod candidates
        # only, not every pod in the cluster.
        self._pod_informers = [
            Informer(clients.pods, namespace=ns,
                     indexers={"cd-uid": pod_cd_uid})
            for ns in self._managed_namespaces()]
        self._clique_informer = Informer(clients.compute_domain_cliques,
                                         indexers={"cd-uid": clique_cd_uid})
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def event_recorder(self) -> EventRecorder:
        """The controller's Event sink — shared with the SLO engine so
        SLOBurnRate Warnings ride the same deduped async pipeline."""
        return self._events_rec

    def start(self) -> None:
        self._cd_informer.add_handlers(
            on_add=self._on_cd_event,
            on_update=self._on_cd_update)
        if self._config.event_driven:
            # Pod/clique events drive status convergence; the handlers are
            # registered before start() so the initial ADDED replay warms
            # every existing CD's status key.
            for inf in self._pod_informers:
                inf.add_handlers(
                    on_add=lambda o: self._enqueue_status_for(o, "pod"),
                    on_update=lambda old, new: self._enqueue_status_for(
                        new, "pod", old),
                    on_delete=lambda o: self._enqueue_status_for(o, "pod"))
                inf.start()
            self._clique_informer.add_handlers(
                on_add=lambda o: self._enqueue_status_for(o, "clique"),
                on_update=lambda old, new: self._enqueue_status_for(
                    new, "clique"),
                on_delete=lambda o: self._enqueue_status_for(o, "clique"))
            self._clique_informer.start()
        self._cd_informer.start()
        if self._config.event_driven:
            for inf in self._pod_informers:
                inf.wait_synced()
            self._clique_informer.wait_synced()
        self._cd_informer.wait_synced()
        self._queue.start(workers=max(1, self._config.workers))
        for name, fn, interval in (
            ("cd-status-sync", self._sync_all_statuses,
             self._config.status_sync_interval),
            ("cd-orphan-cleanup", self._cleanup_orphans,
             self._config.orphan_cleanup_interval),
        ):
            t = threading.Thread(target=self._loop, args=(fn, interval),
                                 name=name, daemon=True)
            t.start()
            self._threads.append(t)
        log.info("compute-domain-controller started (%s status sync, "
                 "%d workers, %.0fs resync backstop)",
                 "event-driven" if self._config.event_driven else "poll",
                 max(1, self._config.workers),
                 self._config.status_sync_interval)

    def stop(self) -> None:
        self._stop.set()
        self._queue.shutdown()
        self._cd_informer.stop()
        if self._config.event_driven:
            for inf in self._pod_informers:
                inf.stop()
            self._clique_informer.stop()
        for t in self._threads:
            t.join(timeout=2.0)
        self._events_rec.stop(timeout=2.0)

    def _loop(self, fn, interval: float) -> None:
        # Run once immediately, THEN wait: a freshly started controller
        # must not sit out a whole interval before its first status sync
        # (2 s) or orphan sweep (600 s).
        while True:
            try:
                fn()
            except Exception:
                from tpu_dra_driver.pkg.metrics import SWALLOWED_ERRORS
                SWALLOWED_ERRORS.labels("controller.periodic").inc()
                log.exception("periodic task failed (retried next tick)")
            if self._stop.wait(interval):
                return

    # ------------------------------------------------------------------
    # reconcile
    # ------------------------------------------------------------------

    def _on_cd_event(self, obj: Dict) -> None:
        self._enqueue(obj)
        uid = obj["metadata"].get("uid", "")
        if uid and self._config.event_driven:
            self._enqueue_status(uid, "cd")

    def _on_cd_update(self, old: Dict, new: Dict) -> None:
        self._enqueue(new)
        # Only a spec change (generation bump) warrants a status re-sync;
        # reacting to our own status writes would re-debounce pending
        # syncs and sync once more just to abort.
        if (self._config.event_driven
                and (old.get("metadata") or {}).get("generation")
                != new["metadata"].get("generation")):
            uid = new["metadata"].get("uid", "")
            if uid:
                self._enqueue_status(uid, "cd")

    def _enqueue(self, obj: Dict) -> None:
        meta = obj["metadata"]
        key = f"{meta.get('namespace','')}/{meta['name']}"
        self._queue.enqueue_with_key(key, lambda: self._reconcile(key))

    def _enqueue_status_for(self, obj: Dict, source: str,
                            old: Optional[Dict] = None) -> None:
        """Enqueue a status sync for every CD uid the object (and, on
        label moves, its previous incarnation) maps to."""
        uids = set()
        for o in (obj, old):
            if o is None:
                continue
            meta = o.get("metadata") or {}
            if source == "pod":
                uid = (meta.get("labels") or {}).get(COMPUTE_DOMAIN_LABEL_KEY)
            else:
                uid = meta.get("name", "").split(".", 1)[0]
            if uid:
                uids.add(uid)
        for uid in uids:
            self._enqueue_status(uid, source)

    def _enqueue_status(self, uid: str, source: str) -> None:
        """Debounced, coalescing per-CD status sync: the keyed queue keeps
        only the newest enqueue per ``status:<uid>`` and each re-enqueue
        pushes the deadline back, so an event burst runs one sync."""
        self._status_triggers.labels(source).inc()
        # Rendezvous clock anchor: the first clique event for a not-yet-
        # Ready CD marks the first daemon join — anchoring at sync time
        # instead would lose the sample entirely when the whole burst
        # coalesces into one straight-to-Ready sync.
        if (source == "clique" and uid not in self._rendezvous_t0
                and self._cd_informer.synced):
            cds = self._cd_informer.by_index("uid", uid)
            if cds and ((cds[0].get("status") or {}).get("status")
                        != STATUS_READY):
                self._rendezvous_t0[uid] = time.monotonic()
                self._start_rendezvous_span(uid, cds[0])
        self._queue.enqueue_with_key(
            f"status:{uid}", lambda: self._sync_cd_status(uid),
            delay=self._config.status_debounce)

    def _start_rendezvous_span(self, uid: str, cd_obj) -> None:
        """Open the ``cd.rendezvous`` span (first daemon join → Ready
        flip) on the CD's own trace — the traceparent annotation stamped
        at first reconcile — so the daemon's clique-render spans from a
        different process land in the same trace."""
        if not tracing.enabled() or uid in self._rendezvous_spans:
            return
        if isinstance(cd_obj, dict):
            ctx = tracing.from_object(cd_obj)
        else:
            ctx = tracing.parse_traceparent(
                (cd_obj.metadata.annotations or {}).get(
                    tracing.TRACEPARENT_ANNOTATION))
        span = tracing.start_span("cd.rendezvous", parent=ctx,
                                  attributes={"cd_uid": uid})
        if span.recording:
            self._rendezvous_spans[uid] = span

    def _reconcile(self, key: str) -> None:
        with self._reconcile_duration.time():
            try:
                self._reconcile_inner(key)
            except Exception:
                self._reconciles.labels("error").inc()
                raise
            self._reconciles.labels("ok").inc()

    def _reconcile_inner(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            obj = self._clients.compute_domains.get(name, ns)
        except NotFoundError:
            return
        cd = ComputeDomain.from_obj(obj)
        if cd.metadata.deletion_timestamp is not None:
            self._teardown(cd)
            return
        # Validation failures are *terminal* for this spec generation: emit
        # an Event the user can see and stop — retrying a permanently
        # invalid object would burn the queue forever with no signal.
        try:
            cd.validate()
            if cd.spec.num_nodes > self._config.max_nodes_per_domain:
                raise ValueError(
                    f"numNodes {cd.spec.num_nodes} exceeds the per-domain "
                    f"cap {self._config.max_nodes_per_domain}"
                )
        except ValueError as e:
            log.error("ComputeDomain %s rejected: %s", key, e)
            self._emit_event(cd, REASON_VALIDATION_FAILED, str(e))
            return
        self._ensure_finalizer(cd)
        self._ensure_children(cd)

    def _cd_ref(self, cd: ComputeDomain) -> Dict[str, str]:
        return object_ref("ComputeDomain", cd.metadata.name,
                          cd.metadata.namespace, cd.metadata.uid)

    def _emit_event(self, cd: ComputeDomain, reason: str, message: str) -> None:
        """Warning event on the CD (deduped/rate-limited; kube/events.py
        swallows API failures by contract)."""
        self._events_rec.warning(self._cd_ref(cd), reason, message)

    def _ensure_finalizer(self, cd: ComputeDomain) -> None:
        # The CD's trace is born here: a fresh root context stamped once,
        # alongside the finalizer, so the daemon's clique renders and
        # this controller's rendezvous span (different processes) all key
        # off one trace id. ONE marker span is created lazily outside the
        # mutate (which retry_update may run several times on conflicts)
        # and recorded only if OUR trace id actually landed on the object
        # — otherwise the recorder would fill with phantom one-span
        # traces nothing can ever join.
        marker = [None]

        def mutate(obj):
            fins = obj["metadata"].setdefault("finalizers", [])
            changed = False
            if COMPUTE_DOMAIN_FINALIZER not in fins:
                fins.append(COMPUTE_DOMAIN_FINALIZER)
                changed = True
            if tracing.enabled() and tracing.from_object(obj) is None:
                if marker[0] is None:
                    marker[0] = tracing.start_span(
                        "cd.created",
                        attributes={"cd": f"{cd.metadata.namespace}/"
                                          f"{cd.metadata.name}",
                                    "cd_uid": cd.metadata.uid})
                if marker[0].recording:
                    tracing.annotate(obj, marker[0].context)
                    changed = True
            if not changed:
                return ABORT
        final = self._clients.compute_domains.retry_update(
            cd.metadata.name, cd.metadata.namespace, mutate)
        span = marker[0]
        if span is not None and span.recording:
            got = tracing.from_object(final)
            if got is not None and got.trace_id == span.context.trace_id:
                span.end()   # our context won: record the trace root
            # else: never ended -> never recorded (a concurrent replica
            # stamped its own, or the write never happened)

    def _managed_namespaces(self) -> List[str]:
        """Driver namespace + additional namespaces, deduplicated
        (reference mnsdaemonset.go:42-48)."""
        seen = {DRIVER_NAMESPACE}
        seen.update(self._config.additional_namespaces)
        return sorted(seen)

    def _find_daemonset(self, cd_uid: str) -> Optional[Dict]:
        """Locate an existing CD DaemonSet in ANY managed namespace
        (reference mnsdaemonset.go:81-90: adopt before create)."""
        for ns in self._managed_namespaces():
            for ds in self._clients.daemonsets.list(
                    namespace=ns,
                    label_selector={COMPUTE_DOMAIN_LABEL_KEY: cd_uid}):
                return ds
        return None

    def _ensure_children(self, cd: ComputeDomain) -> None:
        """Create-or-update children to the desired state (a bare create
        would never propagate spec changes), and delete stale workload RCTs
        left behind by a rename of spec.channel.resourceClaimTemplate.name."""
        desired_ds = build_daemonset(
            cd, image=self._config.daemon_image,
            log_verbosity=self._config.daemon_log_verbosity,
            device_backend=self._config.device_backend,
            log_format=self._config.daemon_log_format,
            http_endpoint=self._config.daemon_http_endpoint)
        existing_ds = self._find_daemonset(cd.metadata.uid)
        if existing_ds is not None:
            # adopt wherever it lives (possibly an additional namespace)
            if existing_ds.get("spec") != desired_ds["spec"]:
                existing_ds["spec"] = desired_ds["spec"]
                self._clients.daemonsets.update(existing_ds)
        else:
            try:
                self._clients.daemonsets.create(desired_ds)
            except AlreadyExistsError:
                pass  # raced with ourselves; next reconcile converges
        for client, obj in (
            (self._clients.resource_claim_templates, build_daemon_rct(cd)),
            (self._clients.resource_claim_templates, build_workload_rct(cd)),
        ):
            try:
                client.create(obj)
            except AlreadyExistsError:
                existing = client.get(obj["metadata"]["name"],
                                      obj["metadata"].get("namespace", ""))
                if existing.get("spec") != obj["spec"]:
                    existing["spec"] = obj["spec"]
                    client.update(existing)
        desired_rct = cd.spec.channel.resource_claim_template_name
        for rct in self._clients.resource_claim_templates.list(
                namespace=cd.metadata.namespace,
                label_selector={COMPUTE_DOMAIN_LABEL_KEY: cd.metadata.uid}):
            name = rct["metadata"]["name"]
            if name != desired_rct and name != daemon_rct_name(cd):
                self._clients.resource_claim_templates.delete_ignore_missing(
                    name, cd.metadata.namespace)

    # ------------------------------------------------------------------
    # teardown (finalizer-driven, reference computedomain.go + cleanup.go)
    # ------------------------------------------------------------------

    def _teardown(self, cd: ComputeDomain) -> None:
        uid = cd.metadata.uid
        self._rendezvous_t0.pop(uid, None)
        span = self._rendezvous_spans.pop(uid, None)
        if span is not None:
            span.end(status="error")  # CD deleted before reaching Ready
        # DaemonSets may live in any managed namespace (mnsdaemonset.go
        # Delete spans all of them); delete by the CD-uid label so an
        # adopted DS with a non-canonical name is torn down too.
        for ns in self._managed_namespaces():
            # build_daemonset always stamps the CD-uid label, so the
            # label-selector delete covers the canonically-named DS too
            for ds in self._clients.daemonsets.list(
                    namespace=ns,
                    label_selector={COMPUTE_DOMAIN_LABEL_KEY: uid}):
                self._clients.daemonsets.delete_ignore_missing(
                    ds["metadata"]["name"], ns)
        self._clients.resource_claim_templates.delete_ignore_missing(
            daemon_rct_name(cd), DRIVER_NAMESPACE)
        self._clients.resource_claim_templates.delete_ignore_missing(
            cd.spec.channel.resource_claim_template_name, cd.metadata.namespace)
        for cq in self._clients.compute_domain_cliques.list():
            if cq["metadata"]["name"].startswith(f"{uid}."):
                self._clients.compute_domain_cliques.delete_ignore_missing(
                    cq["metadata"]["name"], cq["metadata"].get("namespace", ""))
        self._remove_node_labels(uid)

        def drop_finalizer(obj):
            fins = obj["metadata"].get("finalizers") or []
            if COMPUTE_DOMAIN_FINALIZER not in fins:
                return ABORT
            obj["metadata"]["finalizers"] = [
                f for f in fins if f != COMPUTE_DOMAIN_FINALIZER]
        try:
            self._clients.compute_domains.retry_update(
                cd.metadata.name, cd.metadata.namespace, drop_finalizer)
        except NotFoundError:
            pass
        log.info("ComputeDomain %s/%s torn down",
                 cd.metadata.namespace, cd.metadata.name)

    def _remove_node_labels(self, cd_uid: str) -> None:
        """Node-label GC (reference node.go:113-166)."""
        for node in self._clients.nodes.list(label_selector={
                COMPUTE_DOMAIN_LABEL_KEY: cd_uid}):
            def mutate(obj):
                labels = obj["metadata"].get("labels") or {}
                if labels.get(COMPUTE_DOMAIN_LABEL_KEY) != cd_uid:
                    return ABORT
                del labels[COMPUTE_DOMAIN_LABEL_KEY]
            try:
                self._clients.nodes.retry_update(node["metadata"]["name"], "",
                                                 mutate)
            except NotFoundError:
                pass

    def _cleanup_orphans(self) -> None:
        """Children labeled for a CD uid that no longer exists
        (reference cleanup.go:33-160 CleanupManager)."""
        live_uids = {c["metadata"]["uid"]
                     for c in self._clients.compute_domains.list()}
        for client in (self._clients.daemonsets,
                       self._clients.resource_claim_templates):
            for obj in client.list():
                uid = (obj["metadata"].get("labels") or {}).get(
                    COMPUTE_DOMAIN_LABEL_KEY)
                if uid and uid not in live_uids:
                    log.warning("cleaning up orphan %s %s/%s (cd %s gone)",
                                client.resource, obj["metadata"].get("namespace", ""),
                                obj["metadata"]["name"], uid)
                    client.delete_ignore_missing(
                        obj["metadata"]["name"],
                        obj["metadata"].get("namespace", ""))
        for cq in self._clients.compute_domain_cliques.list():
            uid = cq["metadata"]["name"].split(".", 1)[0]
            if uid not in live_uids:
                self._clients.compute_domain_cliques.delete_ignore_missing(
                    cq["metadata"]["name"], cq["metadata"].get("namespace", ""))

    # ------------------------------------------------------------------
    # status sync (reference cdstatus.go:120-260, informer-triggered)
    # ------------------------------------------------------------------

    def _daemon_pods_for(self, cd_uid: str) -> List[Dict]:
        """Daemon pods for one CD. Event-driven: an O(1) lister lookup on
        the pod informer's uid index — zero API round-trips (reference
        daemonsetpods.go DaemonSetPodManager backed by client-go listers).
        Poll arm: the live per-namespace LISTs the old loop paid."""
        if self._config.event_driven:
            out: List[Dict] = []
            for inf in self._pod_informers:
                out.extend(inf.by_index("cd-uid", cd_uid))
            return out
        return self._daemon_pods_live(cd_uid)

    def _daemon_pods_live(self, cd_uid: str) -> List[Dict]:
        """One CD's daemon pods via live label-selector LISTs — the
        authoritative read the prune confirm (and the poll arm) uses."""
        out: List[Dict] = []
        for ns in self._managed_namespaces():
            out.extend(self._clients.pods.list(
                namespace=ns,
                label_selector={COMPUTE_DOMAIN_LABEL_KEY: cd_uid}))
        return out

    def _cliques_for(self, cd_uid: str) -> List[Dict]:
        """This CD's cliques (name ``<cdUID>.<cliqueID>``) from the clique
        informer's uid index (or a live filtered LIST in the poll arm)."""
        if self._config.event_driven:
            return self._clique_informer.by_index("cd-uid", cd_uid)
        return [cq for cq in self._clients.compute_domain_cliques.list()
                if cq["metadata"]["name"].split(".", 1)[0] == cd_uid]

    def _sync_cd_status(self, uid: str) -> None:
        """One CD's status convergence, served entirely from informer
        stores. Raising (e.g. conflict retries exhausted) re-enqueues the
        key with the queue's backoff."""
        cds = self._cd_informer.by_index("uid", uid)
        if not cds:
            return  # CD gone; orphan cleanup owns the leftovers
        cliques = self._cliques_for(uid)
        pods = self._daemon_pods_for(uid)
        try:
            self._cleanup_cliques(uid, cliques, pods)
            self._sync_status(ComputeDomain.from_obj(cds[0]))
        except NotFoundError:
            pass  # deleted mid-sync; a CD event follows

    def _sync_all_statuses(self) -> None:
        """The periodic pass. Event-driven: a resync backstop that only
        re-enqueues per-CD keys (coalescing with any pending event-driven
        sync). Poll arm: the original full-LIST-and-sync tick."""
        if self._config.event_driven:
            for obj in self._cd_informer.list():
                uid = obj["metadata"].get("uid", "")
                if uid:
                    self._enqueue_status(uid, "resync")
            return
        pods_by_cd: Dict[str, List[Dict]] = {}
        for ns in self._managed_namespaces():
            for pod in self._clients.pods.list(namespace=ns):
                uid = (pod["metadata"].get("labels") or {}).get(
                    COMPUTE_DOMAIN_LABEL_KEY)
                if uid:
                    pods_by_cd.setdefault(uid, []).append(pod)
        cliques_by_cd: Dict[str, List[Dict]] = {}
        for cq_obj in self._clients.compute_domain_cliques.list():
            uid = cq_obj["metadata"]["name"].split(".", 1)[0]
            cliques_by_cd.setdefault(uid, []).append(cq_obj)
        for obj in self._clients.compute_domains.list():
            uid = obj["metadata"].get("uid", "")
            self._status_triggers.labels("poll").inc()
            try:
                self._cleanup_cliques(uid, cliques_by_cd.get(uid, []),
                                      pods_by_cd.get(uid, []))
                self._sync_status(ComputeDomain.from_obj(obj))
            except (ConflictError, NotFoundError):
                pass  # next tick

    def _cleanup_cliques(self, cd_uid: str, cliques: List[Dict],
                         pods: List[Dict]) -> None:
        """Remove clique daemon entries whose pod is gone — the heal path
        for force-deleted daemon pods (reference cdstatus.go:286-326
        cleanupClique)."""
        running_nodes = self._pod_nodes(pods)
        for cq_obj in cliques:
            name = cq_obj["metadata"]["name"]
            stale = [d.get("nodeName") for d in cq_obj.get("daemons") or []
                     if d.get("nodeName") not in running_nodes]
            if not stale:
                continue
            # Pruning is destructive and unrecoverable for the daemon
            # (join() only runs at its startup), so before evicting,
            # confirm with ONE live LIST: the pod informer's store can
            # momentarily lag the clique event that triggered this sync
            # (independent watch threads), and evicting a just-joined
            # replacement daemon would strand its node. The live confirm
            # runs only on this rare heal path — the hot status path
            # stays lister-only.
            confirmed_nodes = self._pod_nodes(
                self._daemon_pods_live(cd_uid))

            def prune(obj):
                # Per-retry re-check from the informer's continuously-
                # updated store (was a live per-namespace LIST on every
                # conflict retry), unioned with the one-time live confirm.
                fresh_nodes = (self._pod_nodes(self._daemon_pods_for(cd_uid))
                               | confirmed_nodes)
                daemons = obj.get("daemons") or []
                kept = [d for d in daemons
                        if d.get("nodeName") in fresh_nodes]
                if len(kept) == len(daemons):
                    return ABORT
                obj["daemons"] = kept
            log.info("pruning stale clique entries %s from %s", stale, name)
            try:
                self._clients.compute_domain_cliques.retry_update(
                    name, cq_obj["metadata"].get("namespace", ""), prune)
            except NotFoundError:
                pass

    @staticmethod
    def _pod_nodes(pods: List[Dict]) -> set:
        nodes = {(p.get("spec") or {}).get("nodeName") for p in pods}
        nodes.discard(None)
        nodes.discard("")
        return nodes

    def _compute_status(self, cd: ComputeDomain, uid: str):
        """Desired (nodes, global_status, any-daemon-joined) from the
        CURRENT informer stores (or live LISTs in the poll arm)."""
        cliques = self._cliques_for(uid)
        pods = self._daemon_pods_for(uid)
        nodes: List[ComputeDomainNodeStatus] = []
        for cq_obj in cliques:
            clique_id = cq_obj["metadata"]["name"].split(".", 1)[1]
            cq = ComputeDomainClique.from_obj(cq_obj)
            for d in cq.daemons:
                nodes.append(ComputeDomainNodeStatus(
                    name=d.node_name, ip_address=d.ip_address,
                    clique_id=clique_id, index=d.index, status=d.status))
        # Non-fabric nodes: daemon pods whose clique-id label is explicitly
        # empty contribute status entries built from the pod itself
        # (reference cdstatus.go:258-283 buildNodesFromPods; cliqueID "",
        # index -1, status from pod readiness).
        fabric_nodes = {n.name for n in nodes}
        for pod in pods:
            labels = pod["metadata"].get("labels") or {}
            if labels.get(CLIQUE_ID_LABEL_KEY, "missing") != "":
                continue
            node_name = (pod.get("spec") or {}).get("nodeName", "")
            pod_ip = (pod.get("status") or {}).get("podIP", "")
            if not node_name or not pod_ip or node_name in fabric_nodes:
                continue
            conditions = (pod.get("status") or {}).get("conditions") or []
            ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                        for c in conditions)
            nodes.append(ComputeDomainNodeStatus(
                name=node_name, ip_address=pod_ip, clique_id="", index=-1,
                status=STATUS_READY if ready else STATUS_NOT_READY))
        nodes.sort(key=lambda n: (n.clique_id, n.index))
        ready = sum(1 for n in nodes if n.status == STATUS_READY)
        # multislice: enough ready nodes all piled into one fabric is NOT a
        # usable domain — the ready set must span numSlices distinct
        # cliques (numNodes=0 domains stay Ready-at-zero as before)
        ready_slices = len({n.clique_id for n in nodes
                            if n.status == STATUS_READY and n.clique_id})
        slices_ok = (cd.spec.num_slices <= 1 or cd.spec.num_nodes == 0
                     or ready_slices >= cd.spec.num_slices)
        global_status = (STATUS_READY
                         if ready >= cd.spec.num_nodes and slices_ok
                         else STATUS_NOT_READY)
        has_daemon = any(cq.get("daemons") for cq in cliques)
        return nodes, global_status, has_daemon

    def _sync_status(self, cd: ComputeDomain) -> None:
        uid = cd.metadata.uid
        outcome: Dict[str, object] = {}

        def mutate(obj):
            # Desired state is derived INSIDE the mutate, per attempt:
            # with N workers a stale sync for this CD can run concurrently
            # with (or after) a fresher one, and writing a pre-captured
            # snapshot here would regress the fresher status until the
            # resync backstop — status writes don't bump generation, so no
            # event would heal it.
            cur = ComputeDomain.from_obj(obj)
            nodes, global_status, has_daemon = self._compute_status(cur, uid)
            outcome["status"] = global_status
            outcome["has_daemon"] = has_daemon
            new_nodes = [n.__dict__ for n in nodes]
            old_nodes = [n.__dict__ for n in cur.status.nodes]
            # A CD with no status block yet always gets one stamped (the
            # from_obj defaults equal the initial computed state, so a
            # pure no-change compare would leave a fresh CD status-less
            # until its first daemon appears).
            if ("status" in obj and old_nodes == new_nodes
                    and cur.status.status == global_status):
                outcome.pop("prev_status", None)
                return ABORT
            outcome["prev_status"] = cur.status.status
            cur.status.nodes = nodes
            cur.status.status = global_status
            rendered = cur.to_obj()
            rendered["metadata"] = obj["metadata"]  # keep rv for concurrency
            return rendered

        self._clients.compute_domains.retry_update(
            cd.metadata.name, cd.metadata.namespace, mutate)
        # Rendezvous clock: starts at the first observed daemon join while
        # the CD is converging; observed when the Ready flip is written.
        if outcome.get("status") != STATUS_READY and outcome.get("has_daemon"):
            if uid not in self._rendezvous_t0:
                self._rendezvous_t0[uid] = time.monotonic()
                self._start_rendezvous_span(uid, cd)
        if "prev_status" in outcome:
            self._status_writes.inc()
            if (outcome["status"] == STATUS_READY
                    and outcome["prev_status"] != STATUS_READY):
                span = self._rendezvous_spans.pop(uid, None)
                t0 = self._rendezvous_t0.pop(uid, None)
                if t0 is not None:
                    self._rendezvous_seconds.observe(
                        time.monotonic() - t0,
                        exemplar=tracing.exemplar(span))
                if span is not None:
                    span.end()
                self._events_rec.normal(
                    self._cd_ref(cd), REASON_CD_READY,
                    f"ComputeDomain Ready "
                    f"({cd.spec.num_nodes} node(s) requested)")
