"""The compute-domain-controller: ComputeDomain reconciliation.

Reference analog: cmd/compute-domain-controller/{computedomain.go:298-374,
daemonset.go, resourceclaimtemplate.go, cdstatus.go:120-260, node.go,
cleanup.go}. Responsibilities:

- on CD add/update: add finalizer, stamp the per-CD DaemonSet + daemon
  RCT (driver namespace) + workload RCT (user namespace), enforce the
  max-nodes cap;
- status sync loop (2 s): copy ComputeDomainClique daemon entries into
  ``CD.status.nodes`` and flip the global status Ready when >= numNodes
  nodes are Ready (pruning stale nodes);
- on CD delete: tear down children (DS, RCTs, cliques, node labels), then
  drop the finalizer;
- periodic orphan cleanup: children whose CD no longer exists.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra_driver.api.types import (
    ComputeDomain,
    ComputeDomainClique,
    DEFAULT_MAX_NODES_PER_DOMAIN,
    STATUS_NOT_READY,
    STATUS_READY,
    ComputeDomainNodeStatus,
)
from tpu_dra_driver.computedomain import (
    COMPUTE_DOMAIN_FINALIZER,
    COMPUTE_DOMAIN_LABEL_KEY,
    DRIVER_NAMESPACE,
)
from tpu_dra_driver.computedomain.daemon.daemon import CLIQUE_ID_LABEL_KEY
from tpu_dra_driver.computedomain.controller.objects import (
    build_daemon_rct,
    build_daemonset,
    build_workload_rct,
    daemon_rct_name,
)
from tpu_dra_driver.kube.client import ABORT, ClientSets
from tpu_dra_driver.kube.errors import AlreadyExistsError, ConflictError, NotFoundError
from tpu_dra_driver.kube.informer import Informer
from tpu_dra_driver.pkg.metrics import DEFAULT_REGISTRY, QueueMetrics, Registry
from tpu_dra_driver.pkg.workqueue import WorkQueue, default_controller_rate_limiter

log = logging.getLogger(__name__)

STATUS_SYNC_INTERVAL = 2.0       # reference cdstatus.go: 2 s loop
ORPHAN_CLEANUP_INTERVAL = 600.0


@dataclass
class ControllerConfig:
    max_nodes_per_domain: int = DEFAULT_MAX_NODES_PER_DOMAIN
    status_sync_interval: float = STATUS_SYNC_INTERVAL
    orphan_cleanup_interval: float = ORPHAN_CLEANUP_INTERVAL
    # Extra namespaces where the driver may manage CD DaemonSets
    # (reference mnsdaemonset.go + --additional-namespaces): a CD's
    # DaemonSet found in any managed namespace is adopted/updated there;
    # new ones are always created in the driver namespace; teardown and
    # orphan cleanup span all managed namespaces.
    additional_namespaces: List[str] = field(default_factory=list)
    # hardware backend the stamped CD daemon pods must use; matches the
    # chart-wide deviceBackend value ("fake" on demo clusters)
    device_backend: str = "native"
    # image + verbosity for stamped CD daemon pods ("" → $DRIVER_IMAGE or
    # the objects.DEFAULT_IMAGE fallback; reference plumbs these through
    # the DaemonSet template, daemonset.go:206-217)
    daemon_image: str = ""
    daemon_log_verbosity: int = 4


class ComputeDomainController:
    def __init__(self, clients: ClientSets,
                 config: Optional[ControllerConfig] = None,
                 registry: Optional[Registry] = None):
        self._clients = clients
        self._config = config or ControllerConfig()
        self.registry = registry or DEFAULT_REGISTRY
        self._queue = WorkQueue(default_controller_rate_limiter(),
                                name="cd-controller",
                                metrics=QueueMetrics("cd-controller",
                                                     self.registry))
        self._reconciles = self.registry.counter(
            "computedomain_reconciles_total",
            "ComputeDomain reconcile attempts by result", ("result",))
        self._reconcile_duration = self.registry.histogram(
            "computedomain_reconcile_duration_seconds",
            "Wall time of one ComputeDomain reconcile")
        self._cd_informer = Informer(clients.compute_domains)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._cd_informer.add_handlers(
            on_add=self._enqueue, on_update=lambda old, new: self._enqueue(new))
        self._cd_informer.start()
        self._cd_informer.wait_synced()
        self._queue.start(workers=1)
        for name, fn, interval in (
            ("cd-status-sync", self._sync_all_statuses,
             self._config.status_sync_interval),
            ("cd-orphan-cleanup", self._cleanup_orphans,
             self._config.orphan_cleanup_interval),
        ):
            t = threading.Thread(target=self._loop, args=(fn, interval),
                                 name=name, daemon=True)
            t.start()
            self._threads.append(t)
        log.info("compute-domain-controller started")

    def stop(self) -> None:
        self._stop.set()
        self._queue.shutdown()
        self._cd_informer.stop()
        for t in self._threads:
            t.join(timeout=2.0)

    def _loop(self, fn, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                fn()
            except Exception:
                log.exception("periodic task failed")

    # ------------------------------------------------------------------
    # reconcile
    # ------------------------------------------------------------------

    def _enqueue(self, obj: Dict) -> None:
        meta = obj["metadata"]
        key = f"{meta.get('namespace','')}/{meta['name']}"
        self._queue.enqueue_with_key(key, lambda: self._reconcile(key))

    def _reconcile(self, key: str) -> None:
        with self._reconcile_duration.time():
            try:
                self._reconcile_inner(key)
            except Exception:
                self._reconciles.labels("error").inc()
                raise
            self._reconciles.labels("ok").inc()

    def _reconcile_inner(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            obj = self._clients.compute_domains.get(name, ns)
        except NotFoundError:
            return
        cd = ComputeDomain.from_obj(obj)
        if cd.metadata.deletion_timestamp is not None:
            self._teardown(cd)
            return
        # Validation failures are *terminal* for this spec generation: emit
        # an Event the user can see and stop — retrying a permanently
        # invalid object would burn the queue forever with no signal.
        try:
            cd.validate()
            if cd.spec.num_nodes > self._config.max_nodes_per_domain:
                raise ValueError(
                    f"numNodes {cd.spec.num_nodes} exceeds the per-domain "
                    f"cap {self._config.max_nodes_per_domain}"
                )
        except ValueError as e:
            log.error("ComputeDomain %s rejected: %s", key, e)
            self._emit_event(cd, "ValidationFailed", str(e))
            return
        self._ensure_finalizer(cd)
        self._ensure_children(cd)

    def _emit_event(self, cd: ComputeDomain, reason: str, message: str) -> None:
        try:
            self._clients.events.create({
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {"generateName": f"{cd.metadata.name}.",
                             "namespace": cd.metadata.namespace or "default"},
                "type": "Warning",
                "reason": reason,
                "message": message,
                "involvedObject": {"kind": "ComputeDomain",
                                   "name": cd.metadata.name,
                                   "namespace": cd.metadata.namespace,
                                   "uid": cd.metadata.uid},
            })
        except Exception:
            log.exception("failed to emit event for %s", cd.metadata.name)

    def _ensure_finalizer(self, cd: ComputeDomain) -> None:
        def mutate(obj):
            fins = obj["metadata"].setdefault("finalizers", [])
            if COMPUTE_DOMAIN_FINALIZER in fins:
                return ABORT
            fins.append(COMPUTE_DOMAIN_FINALIZER)
        self._clients.compute_domains.retry_update(
            cd.metadata.name, cd.metadata.namespace, mutate)

    def _managed_namespaces(self) -> List[str]:
        """Driver namespace + additional namespaces, deduplicated
        (reference mnsdaemonset.go:42-48)."""
        seen = {DRIVER_NAMESPACE}
        seen.update(self._config.additional_namespaces)
        return sorted(seen)

    def _find_daemonset(self, cd_uid: str) -> Optional[Dict]:
        """Locate an existing CD DaemonSet in ANY managed namespace
        (reference mnsdaemonset.go:81-90: adopt before create)."""
        for ns in self._managed_namespaces():
            for ds in self._clients.daemonsets.list(
                    namespace=ns,
                    label_selector={COMPUTE_DOMAIN_LABEL_KEY: cd_uid}):
                return ds
        return None

    def _ensure_children(self, cd: ComputeDomain) -> None:
        """Create-or-update children to the desired state (a bare create
        would never propagate spec changes), and delete stale workload RCTs
        left behind by a rename of spec.channel.resourceClaimTemplate.name."""
        desired_ds = build_daemonset(
            cd, image=self._config.daemon_image,
            log_verbosity=self._config.daemon_log_verbosity,
            device_backend=self._config.device_backend)
        existing_ds = self._find_daemonset(cd.metadata.uid)
        if existing_ds is not None:
            # adopt wherever it lives (possibly an additional namespace)
            if existing_ds.get("spec") != desired_ds["spec"]:
                existing_ds["spec"] = desired_ds["spec"]
                self._clients.daemonsets.update(existing_ds)
        else:
            try:
                self._clients.daemonsets.create(desired_ds)
            except AlreadyExistsError:
                pass  # raced with ourselves; next reconcile converges
        for client, obj in (
            (self._clients.resource_claim_templates, build_daemon_rct(cd)),
            (self._clients.resource_claim_templates, build_workload_rct(cd)),
        ):
            try:
                client.create(obj)
            except AlreadyExistsError:
                existing = client.get(obj["metadata"]["name"],
                                      obj["metadata"].get("namespace", ""))
                if existing.get("spec") != obj["spec"]:
                    existing["spec"] = obj["spec"]
                    client.update(existing)
        desired_rct = cd.spec.channel.resource_claim_template_name
        for rct in self._clients.resource_claim_templates.list(
                namespace=cd.metadata.namespace,
                label_selector={COMPUTE_DOMAIN_LABEL_KEY: cd.metadata.uid}):
            name = rct["metadata"]["name"]
            if name != desired_rct and name != daemon_rct_name(cd):
                self._clients.resource_claim_templates.delete_ignore_missing(
                    name, cd.metadata.namespace)

    # ------------------------------------------------------------------
    # teardown (finalizer-driven, reference computedomain.go + cleanup.go)
    # ------------------------------------------------------------------

    def _teardown(self, cd: ComputeDomain) -> None:
        uid = cd.metadata.uid
        # DaemonSets may live in any managed namespace (mnsdaemonset.go
        # Delete spans all of them); delete by the CD-uid label so an
        # adopted DS with a non-canonical name is torn down too.
        for ns in self._managed_namespaces():
            # build_daemonset always stamps the CD-uid label, so the
            # label-selector delete covers the canonically-named DS too
            for ds in self._clients.daemonsets.list(
                    namespace=ns,
                    label_selector={COMPUTE_DOMAIN_LABEL_KEY: uid}):
                self._clients.daemonsets.delete_ignore_missing(
                    ds["metadata"]["name"], ns)
        self._clients.resource_claim_templates.delete_ignore_missing(
            daemon_rct_name(cd), DRIVER_NAMESPACE)
        self._clients.resource_claim_templates.delete_ignore_missing(
            cd.spec.channel.resource_claim_template_name, cd.metadata.namespace)
        for cq in self._clients.compute_domain_cliques.list():
            if cq["metadata"]["name"].startswith(f"{uid}."):
                self._clients.compute_domain_cliques.delete_ignore_missing(
                    cq["metadata"]["name"], cq["metadata"].get("namespace", ""))
        self._remove_node_labels(uid)

        def drop_finalizer(obj):
            fins = obj["metadata"].get("finalizers") or []
            if COMPUTE_DOMAIN_FINALIZER not in fins:
                return ABORT
            obj["metadata"]["finalizers"] = [
                f for f in fins if f != COMPUTE_DOMAIN_FINALIZER]
        try:
            self._clients.compute_domains.retry_update(
                cd.metadata.name, cd.metadata.namespace, drop_finalizer)
        except NotFoundError:
            pass
        log.info("ComputeDomain %s/%s torn down",
                 cd.metadata.namespace, cd.metadata.name)

    def _remove_node_labels(self, cd_uid: str) -> None:
        """Node-label GC (reference node.go:113-166)."""
        for node in self._clients.nodes.list(label_selector={
                COMPUTE_DOMAIN_LABEL_KEY: cd_uid}):
            def mutate(obj):
                labels = obj["metadata"].get("labels") or {}
                if labels.get(COMPUTE_DOMAIN_LABEL_KEY) != cd_uid:
                    return ABORT
                del labels[COMPUTE_DOMAIN_LABEL_KEY]
            try:
                self._clients.nodes.retry_update(node["metadata"]["name"], "",
                                                 mutate)
            except NotFoundError:
                pass

    def _cleanup_orphans(self) -> None:
        """Children labeled for a CD uid that no longer exists
        (reference cleanup.go:33-160 CleanupManager)."""
        live_uids = {c["metadata"]["uid"]
                     for c in self._clients.compute_domains.list()}
        for client in (self._clients.daemonsets,
                       self._clients.resource_claim_templates):
            for obj in client.list():
                uid = (obj["metadata"].get("labels") or {}).get(
                    COMPUTE_DOMAIN_LABEL_KEY)
                if uid and uid not in live_uids:
                    log.warning("cleaning up orphan %s %s/%s (cd %s gone)",
                                client.resource, obj["metadata"].get("namespace", ""),
                                obj["metadata"]["name"], uid)
                    client.delete_ignore_missing(
                        obj["metadata"]["name"],
                        obj["metadata"].get("namespace", ""))
        for cq in self._clients.compute_domain_cliques.list():
            uid = cq["metadata"]["name"].split(".", 1)[0]
            if uid not in live_uids:
                self._clients.compute_domain_cliques.delete_ignore_missing(
                    cq["metadata"]["name"], cq["metadata"].get("namespace", ""))

    # ------------------------------------------------------------------
    # status sync (reference cdstatus.go:120-260)
    # ------------------------------------------------------------------

    def _daemon_pods_by_cd(self) -> Dict[str, List[Dict]]:
        """Daemon pods grouped by CD uid, across all managed namespaces
        (reference daemonsetpods.go DaemonSetPodManager.List)."""
        by_cd: Dict[str, List[Dict]] = {}
        for ns in self._managed_namespaces():
            for pod in self._clients.pods.list(namespace=ns):
                uid = (pod["metadata"].get("labels") or {}).get(
                    COMPUTE_DOMAIN_LABEL_KEY)
                if uid:
                    by_cd.setdefault(uid, []).append(pod)
        return by_cd

    def _cliques_by_cd(self) -> Dict[str, List[Dict]]:
        """One cluster-wide clique LIST per tick, grouped by CD uid (the
        clique name is ``<cdUID>.<cliqueID>``)."""
        by_cd: Dict[str, List[Dict]] = {}
        for cq_obj in self._clients.compute_domain_cliques.list():
            uid = cq_obj["metadata"]["name"].split(".", 1)[0]
            by_cd.setdefault(uid, []).append(cq_obj)
        return by_cd

    def _sync_all_statuses(self) -> None:
        pods_by_cd = self._daemon_pods_by_cd()
        cliques_by_cd = self._cliques_by_cd()
        for obj in self._clients.compute_domains.list():
            uid = obj["metadata"].get("uid", "")
            try:
                self._cleanup_cliques(uid, cliques_by_cd.get(uid, []),
                                      pods_by_cd.get(uid, []))
                self._sync_status(ComputeDomain.from_obj(obj),
                                  cliques_by_cd.get(uid, []),
                                  pods_by_cd.get(uid, []))
            except (ConflictError, NotFoundError):
                pass  # next tick

    def _cleanup_cliques(self, cd_uid: str, cliques: List[Dict],
                         pods: List[Dict]) -> None:
        """Remove clique daemon entries whose pod is gone — the heal path
        for force-deleted daemon pods (reference cdstatus.go:286-326
        cleanupClique)."""
        running_nodes = self._pod_nodes(pods)
        for cq_obj in cliques:
            name = cq_obj["metadata"]["name"]
            stale = [d.get("nodeName") for d in cq_obj.get("daemons") or []
                     if d.get("nodeName") not in running_nodes]
            if not stale:
                continue

            def prune(obj):
                # Re-list pods inside the mutate: the tick's snapshot may
                # predate a replacement daemon's join (DS rolling update),
                # and evicting a just-joined entry would strand the node —
                # join() only runs at daemon startup.
                fresh_nodes = self._pod_nodes(self._daemon_pods_for(cd_uid))
                daemons = obj.get("daemons") or []
                kept = [d for d in daemons
                        if d.get("nodeName") in fresh_nodes]
                if len(kept) == len(daemons):
                    return ABORT
                obj["daemons"] = kept
            log.info("pruning stale clique entries %s from %s", stale, name)
            try:
                self._clients.compute_domain_cliques.retry_update(
                    name, cq_obj["metadata"].get("namespace", ""), prune)
            except NotFoundError:
                pass

    @staticmethod
    def _pod_nodes(pods: List[Dict]) -> set:
        nodes = {(p.get("spec") or {}).get("nodeName") for p in pods}
        nodes.discard(None)
        nodes.discard("")
        return nodes

    def _daemon_pods_for(self, cd_uid: str) -> List[Dict]:
        out: List[Dict] = []
        for ns in self._managed_namespaces():
            out.extend(self._clients.pods.list(
                namespace=ns,
                label_selector={COMPUTE_DOMAIN_LABEL_KEY: cd_uid}))
        return out

    def _sync_status(self, cd: ComputeDomain, cliques: List[Dict],
                     pods: List[Dict]) -> None:
        nodes: List[ComputeDomainNodeStatus] = []
        for cq_obj in cliques:
            clique_id = cq_obj["metadata"]["name"].split(".", 1)[1]
            cq = ComputeDomainClique.from_obj(cq_obj)
            for d in cq.daemons:
                nodes.append(ComputeDomainNodeStatus(
                    name=d.node_name, ip_address=d.ip_address,
                    clique_id=clique_id, index=d.index, status=d.status))
        # Non-fabric nodes: daemon pods whose clique-id label is explicitly
        # empty contribute status entries built from the pod itself
        # (reference cdstatus.go:258-283 buildNodesFromPods; cliqueID "",
        # index -1, status from pod readiness).
        fabric_nodes = {n.name for n in nodes}
        for pod in pods:
            labels = pod["metadata"].get("labels") or {}
            if labels.get(CLIQUE_ID_LABEL_KEY, "missing") != "":
                continue
            node_name = (pod.get("spec") or {}).get("nodeName", "")
            pod_ip = (pod.get("status") or {}).get("podIP", "")
            if not node_name or not pod_ip or node_name in fabric_nodes:
                continue
            conditions = (pod.get("status") or {}).get("conditions") or []
            ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                        for c in conditions)
            nodes.append(ComputeDomainNodeStatus(
                name=node_name, ip_address=pod_ip, clique_id="", index=-1,
                status=STATUS_READY if ready else STATUS_NOT_READY))
        nodes.sort(key=lambda n: (n.clique_id, n.index))
        ready = sum(1 for n in nodes if n.status == STATUS_READY)
        # multislice: enough ready nodes all piled into one fabric is NOT a
        # usable domain — the ready set must span numSlices distinct
        # cliques (numNodes=0 domains stay Ready-at-zero as before)
        ready_slices = len({n.clique_id for n in nodes
                            if n.status == STATUS_READY and n.clique_id})
        slices_ok = (cd.spec.num_slices <= 1 or cd.spec.num_nodes == 0
                     or ready_slices >= cd.spec.num_slices)
        global_status = (STATUS_READY
                         if ready >= cd.spec.num_nodes and slices_ok
                         else STATUS_NOT_READY)

        def mutate(obj):
            cur = ComputeDomain.from_obj(obj)
            new_nodes = [n.__dict__ for n in nodes]
            old_nodes = [n.__dict__ for n in cur.status.nodes]
            if old_nodes == new_nodes and cur.status.status == global_status:
                return ABORT
            cur.status.nodes = nodes
            cur.status.status = global_status
            rendered = cur.to_obj()
            rendered["metadata"] = obj["metadata"]  # keep rv for concurrency
            return rendered

        self._clients.compute_domains.retry_update(
            cd.metadata.name, cd.metadata.namespace, mutate)
