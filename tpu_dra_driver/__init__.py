"""tpu_dra_driver — a TPU-native Kubernetes Dynamic Resource Allocation driver.

A from-scratch rebuild of the capabilities of the NVIDIA DRA GPU driver
(reference: /root/reference, surveyed in SURVEY.md), designed TPU-first:

- ``tpulib``     — native device boundary: TPU chip enumeration (/dev/accel*,
                   /dev/vfio, PCI vendor 0x1ae0), generation/topology model,
                   per-megacore sub-slice partitioning (the MIG analog), with
                   both a C++ native backend and a faithful in-memory fake.
- ``plugin``     — the tpu-kubelet-plugin: ResourceSlice publishing (incl.
                   KEP-4815 partitionable devices), checkpointed two-phase
                   Prepare/Unprepare, CDI spec generation, sharing managers.
- ``computedomain`` — the ComputeDomain control plane: cluster controller,
                   per-node daemon, and the compute-domain kubelet plugin that
                   orchestrate multi-host ICI slice topology (worker IDs,
                   hostnames, readiness-gated workload release) in place of
                   the reference's IMEX daemons/channels.
- ``kube``       — self-contained Kubernetes client machinery (typed client,
                   in-memory fake API server with watch, informers/listers,
                   leader election) replacing client-go.
- ``pkg``        — substrate-agnostic utilities: feature gates, flock,
                   rate-limited workqueues.
- ``cdi``        — TPU-native CDI spec generation (no NVIDIA Container
                   Toolkit): device nodes, libtpu mounts, TPU_* env.
- ``workloads``  — JAX validation workloads (the nickelpie/nvbandwidth
                   analog): sharded training step + ICI allreduce benchmarks.
"""

from tpu_dra_driver.version import VERSION as __version__  # noqa: F401

DRIVER_NAME = "tpu.google.com"
COMPUTE_DOMAIN_DRIVER_NAME = "compute-domain.tpu.google.com"
API_GROUP = "resource.tpu.google.com"
API_VERSION = "v1beta1"
