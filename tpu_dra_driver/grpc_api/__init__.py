"""grpc_api — kubelet-facing gRPC transport for the DRA plugins.

Generated message modules (``*_pb2.py``, via ``protoc --python_out``) plus
hand-rolled service bindings (grpc generic handlers — the image ships no
grpc_python_plugin, and the service surface is two RPCs per API).

Regenerate after editing the .proto files:
    cd tpu_dra_driver/grpc_api && protoc --python_out=. *.proto
"""
