"""Self-probing kubelet-plugin healthcheck service.

Reference analog: ``cmd/gpu-kubelet-plugin/health.go:51-149`` (same file in
the compute-domain plugin). The container's startup/liveness probes are gRPC
probes against a TCP port; the service behind that port does NOT report its
own in-process state — on every ``Check`` it dials the plugin's two unix
sockets and performs an end-to-end self-probe:

1. ``GetInfo`` on the registration socket (proves the kubelet plugin
   watcher can still discover us), and
2. a **noop** ``NodePrepareResources`` on ``dra.sock`` (proves the DRA
   service is actually serving, not just bound).

Only if both round-trips succeed does it answer ``SERVING``. Known service
names are ``""`` and ``"liveness"`` (reference health.go:122); anything else
is a NOT_FOUND error, which lets probe configs detect typos instead of
silently probing a default service.
"""

from __future__ import annotations

import logging
import threading
from concurrent import futures
from typing import Optional

import grpc

from tpu_dra_driver.grpc_api import dra_v1_pb2 as dra_pb
from tpu_dra_driver.grpc_api.server import DRA_SERVICE_V1
from tpu_dra_driver.grpc_api import health_v1_pb2 as health_pb
from tpu_dra_driver.grpc_api import pluginregistration_v1_pb2 as reg_pb

log = logging.getLogger(__name__)

HEALTH_SERVICE = "grpc.health.v1.Health"
KNOWN_SERVICES = ("", "liveness")
_PROBE_TIMEOUT_S = 4.0


class SelfProbeHealthcheck:
    """gRPC health service on TCP that probes the plugin's own sockets.

    ``registration_target`` / ``dra_target`` are grpc dial targets
    (``unix:///path/to/sock`` in production, ``localhost:<port>`` in
    tests). ``port=0`` binds an ephemeral port (tests); the bound port is
    exposed as ``.port``.
    """

    def __init__(self, registration_target: str, dra_target: str,
                 port: int = 0, host: str = "0.0.0.0",
                 healthy_fn=None):
        """``healthy_fn`` (optional, () -> bool) folds the plugin's own
        health state (e.g. device-health monitor) into the probe on top of
        the two socket round-trips — a strict superset of the reference's
        probe, preserving kubelet restarts on persistent device faults."""
        self._reg_target = registration_target
        self._dra_target = dra_target
        self._healthy_fn = healthy_fn
        self._lock = threading.Lock()
        self._reg_channel: Optional[grpc.Channel] = None
        self._dra_channel: Optional[grpc.Channel] = None
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    # -- channel management (lazy, reused across probes like the
    #    reference's long-lived grpc.NewClient connections) -------------
    def _channels(self):
        with self._lock:
            if self._reg_channel is None:
                self._reg_channel = grpc.insecure_channel(self._reg_target)
            if self._dra_channel is None:
                self._dra_channel = grpc.insecure_channel(self._dra_target)
            return self._reg_channel, self._dra_channel

    def _probe(self) -> bool:
        """One end-to-end self-probe; True iff both sockets answered (and
        the plugin's own health hook, when wired, agrees)."""
        if self._healthy_fn is not None:
            try:
                if not self._healthy_fn():
                    log.error("healthcheck: plugin reports unhealthy")
                    return False
            except Exception:
                log.exception("healthcheck: healthy_fn raised")
                return False
        reg, dra = self._channels()
        try:
            info = reg.unary_unary(
                "/pluginregistration.Registration/GetInfo",
                request_serializer=reg_pb.InfoRequest.SerializeToString,
                response_deserializer=reg_pb.PluginInfo.FromString,
            )(reg_pb.InfoRequest(), timeout=_PROBE_TIMEOUT_S)
            log.debug("healthcheck: GetInfo ok: %s", info.name)
        except grpc.RpcError as exc:
            log.error("healthcheck: GetInfo failed: %s", exc)
            return False
        try:
            dra.unary_unary(
                f"/{DRA_SERVICE_V1}/NodePrepareResources",
                request_serializer=(
                    dra_pb.NodePrepareResourcesRequest.SerializeToString),
                response_deserializer=(
                    dra_pb.NodePrepareResourcesResponse.FromString),
            )(dra_pb.NodePrepareResourcesRequest(), timeout=_PROBE_TIMEOUT_S)
            log.debug("healthcheck: noop NodePrepareResources ok")
        except grpc.RpcError as exc:
            log.error("healthcheck: noop NodePrepareResources failed: %s", exc)
            return False
        return True

    def _handlers(self) -> grpc.GenericRpcHandler:
        def check(request: health_pb.HealthCheckRequest, context):
            if request.service not in KNOWN_SERVICES:
                context.abort(grpc.StatusCode.NOT_FOUND, "unknown service")
            ok = self._probe()
            return health_pb.HealthCheckResponse(
                status=(health_pb.HealthCheckResponse.SERVING if ok
                        else health_pb.HealthCheckResponse.NOT_SERVING))

        return grpc.method_handlers_generic_handler(HEALTH_SERVICE, {
            "Check": grpc.unary_unary_rpc_method_handler(
                check,
                request_deserializer=health_pb.HealthCheckRequest.FromString,
                response_serializer=(
                    health_pb.HealthCheckResponse.SerializeToString),
            ),
        })

    def start(self) -> None:
        self._server.start()
        log.info("healthcheck service listening on port %d", self.port)

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)
        with self._lock:
            for ch in (self._reg_channel, self._dra_channel):
                if ch is not None:
                    ch.close()
            self._reg_channel = self._dra_channel = None
