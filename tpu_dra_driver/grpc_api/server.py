"""DRA kubelet-plugin gRPC server: registration socket + dra.sock.

Reference analog: the k8s.io/dynamic-resource-allocation kubeletplugin
Helper (cmd/gpu-kubelet-plugin/driver.go:123-136): two unix sockets —

- ``<plugins_registry>/<driver>-reg.sock`` serving the Registration API
  (kubelet's plugin watcher discovers it and calls GetInfo),
- ``<plugin_dir>/dra.sock`` serving the DRAPlugin API
  (NodePrepareResources / NodeUnprepareResources),

plus the gRPC health service used by the container's startup/liveness
probes (reference health.go:51-110).

The servicer is transport-only: it resolves claim references to full
ResourceClaim objects via the API client and delegates to the
transport-independent plugin core (prepare_resource_claims /
unprepare_resource_claims), which is what unit tests drive directly.
"""

from __future__ import annotations

import logging
import os
from concurrent import futures
from typing import Callable, Dict, List, Optional

import grpc

from tpu_dra_driver.grpc_api import dra_health_v1alpha1_pb2 as dra_health_pb
from tpu_dra_driver.grpc_api import dra_v1_pb2
from tpu_dra_driver.grpc_api import dra_v1beta1_pb2
from tpu_dra_driver.grpc_api import health_v1_pb2 as health_pb
from tpu_dra_driver.grpc_api import pluginregistration_v1_pb2 as reg_pb
from tpu_dra_driver.kube.client import ResourceClient
from tpu_dra_driver.kube.errors import NotFoundError
from tpu_dra_driver.pkg import faultinject as fi

log = logging.getLogger(__name__)

fi.register("grpc.node_prepare",
            "NodePrepareResources at the gRPC boundary (fail = kubelet "
            "sees an RPC error and retries the whole batch)")
fi.register("grpc.node_unprepare",
            "NodeUnprepareResources at the gRPC boundary")

# Full gRPC service names — the method paths kubelet actually dials
# (reference vendor k8s.io/kubelet/pkg/apis/dra/{v1,v1beta1}/api.pb.go
# ServiceName). Both are served, matching kubeletplugin/draplugin.go:618-657.
DRA_SERVICE_V1 = "k8s.io.kubelet.pkg.apis.dra.v1.DRAPlugin"
DRA_SERVICE_V1BETA1 = "k8s.io.kubelet.pkg.apis.dra.v1beta1.DRAPlugin"
_DRA_PB = {"v1": dra_v1_pb2, "v1beta1": dra_v1beta1_pb2}
_DRA_SERVICE = {"v1": DRA_SERVICE_V1, "v1beta1": DRA_SERVICE_V1BETA1}
REGISTRATION_SERVICE = "pluginregistration.Registration"
HEALTH_SERVICE = "grpc.health.v1.Health"
DRA_HEALTH_SERVICE = "v1alpha1.DRAResourceHealth"
# Version strings advertised to kubelet's plugin watcher, highest first
# (reference v1/types.go:23 "v1.DRAPlugin", v1beta1/types.go:23
# "v1beta1.DRAPlugin"; order per draplugin.go:618-621; the device-health
# stream is appended when served, draplugin.go:623-627).
SUPPORTED_VERSIONS = ("v1.DRAPlugin", "v1beta1.DRAPlugin")


def _dra_health_handlers(plugin) -> grpc.GenericRpcHandler:
    """kubelet's per-device health stream (KEP-4680): an initial snapshot
    followed by a response on every health transition. The reference
    vendors but never implements this service; the TPU health monitor
    feeds it directly."""

    def watch(request, context):
        sent = None    # last version actually yielded
        while context.is_active():
            version = plugin.wait_health_change(
                -1 if sent is None else sent, timeout=30.0)
            if version is None:
                return               # plugin shutting down: end the stream
            if sent is not None and version == sent:
                continue             # poll timeout, nothing changed
            resp = dra_health_pb.NodeWatchResourcesResponse()
            for d in plugin.device_health():
                dh = resp.devices.add()
                dh.device.pool_name = d["pool"]
                dh.device.device_name = d["device"]
                dh.health = (dra_health_pb.HealthStatus.HEALTHY
                             if d["healthy"]
                             else dra_health_pb.HealthStatus.UNHEALTHY)
                dh.last_updated_time = int(d["stamp"])
            sent = version
            yield resp

    return grpc.method_handlers_generic_handler(DRA_HEALTH_SERVICE, {
        "NodeWatchResources": grpc.unary_stream_rpc_method_handler(
            watch,
            request_deserializer=(
                dra_health_pb.NodeWatchResourcesRequest.FromString),
            response_serializer=(
                dra_health_pb.NodeWatchResourcesResponse.SerializeToString),
        ),
    })


def _health_handlers(status_fn: Callable[[], bool]) -> grpc.GenericRpcHandler:
    """grpc.health.v1 via generic handlers (no grpc_health package in the
    image). ``status_fn`` is polled per Check so probes see live state."""

    def check(request: health_pb.HealthCheckRequest, context):
        serving = status_fn()
        return health_pb.HealthCheckResponse(
            status=(health_pb.HealthCheckResponse.SERVING if serving
                    else health_pb.HealthCheckResponse.NOT_SERVING))

    return grpc.method_handlers_generic_handler(HEALTH_SERVICE, {
        "Check": grpc.unary_unary_rpc_method_handler(
            check,
            request_deserializer=health_pb.HealthCheckRequest.FromString,
            response_serializer=health_pb.HealthCheckResponse.SerializeToString,
        ),
    })


def _dra_handlers(plugin, claims_client: ResourceClient,
                  api_version: str) -> grpc.GenericRpcHandler:
    """Build one DRAPlugin service (v1 or v1beta1) from generic method
    handlers. The two versions are wire-identical message-for-message
    (reference conversion.go wraps one server for both); only the package
    prefix in the method path differs."""
    dra_pb = _DRA_PB[api_version]

    def node_prepare(request, context):
        fi.fire("grpc.node_prepare")
        response = dra_pb.NodePrepareResourcesResponse()
        full_claims: List[Dict] = []
        missing: Dict[str, str] = {}
        for ref in request.claims:
            try:
                obj = claims_client.get(ref.name, ref.namespace)
            except NotFoundError:
                missing[ref.uid] = (f"ResourceClaim {ref.namespace}/{ref.name} "
                                    f"not found")
                continue
            if obj["metadata"].get("uid") != ref.uid:
                missing[ref.uid] = (
                    f"ResourceClaim {ref.namespace}/{ref.name}: UID mismatch")
                continue
            full_claims.append(obj)
        results = plugin.prepare_resource_claims(full_claims)
        for uid, err in missing.items():
            response.claims[uid].error = err
        for uid, res in results.items():
            out = response.claims[uid]
            if res.error is not None:
                out.error = res.error
                continue
            for dev in res.devices:
                d = out.devices.add()
                d.request_names.append(dev.request)
                d.pool_name = dev.pool
                d.device_name = dev.canonical_name
                d.cdi_device_ids.extend(dev.cdi_device_ids)
        return response

    def node_unprepare(request, context):
        fi.fire("grpc.node_unprepare")
        response = dra_pb.NodeUnprepareResourcesResponse()
        # full refs (not bare uids) so the plugin can emit Unprepared
        # Events against the named claim
        results = plugin.unprepare_resource_claims(
            [{"uid": ref.uid, "name": ref.name, "namespace": ref.namespace}
             for ref in request.claims])
        for uid, err in results.items():
            if err is not None:
                response.claims[uid].error = err
            else:
                response.claims[uid].SetInParent()
        return response

    handlers = {
        "NodePrepareResources": grpc.unary_unary_rpc_method_handler(
            node_prepare,
            request_deserializer=dra_pb.NodePrepareResourcesRequest.FromString,
            response_serializer=dra_pb.NodePrepareResourcesResponse.SerializeToString,
        ),
        "NodeUnprepareResources": grpc.unary_unary_rpc_method_handler(
            node_unprepare,
            request_deserializer=dra_pb.NodeUnprepareResourcesRequest.FromString,
            response_serializer=dra_pb.NodeUnprepareResourcesResponse.SerializeToString,
        ),
    }
    return grpc.method_handlers_generic_handler(_DRA_SERVICE[api_version],
                                                handlers)


def _registration_handlers(driver_name: str, endpoint_path: str,
                           on_status: Optional[Callable[[bool, str], None]] = None,
                           supported_versions=None) -> grpc.GenericRpcHandler:
    versions = list(supported_versions or SUPPORTED_VERSIONS)

    def get_info(request: reg_pb.InfoRequest, context):
        # kubelet dials `endpoint` as a filesystem socket PATH (not a grpc
        # target) and reads supported_versions as provided *service* names
        # (reference vendor kubeletplugin/registrationserver.go:49-50,
        # noderegistrar.go:39)
        return reg_pb.PluginInfo(
            type="DRAPlugin", name=driver_name, endpoint=endpoint_path,
            supported_versions=versions)

    def notify(request: reg_pb.RegistrationStatus, context):
        if on_status:
            on_status(request.plugin_registered, request.error)
        if not request.plugin_registered:
            log.error("kubelet rejected plugin registration: %s", request.error)
        else:
            log.info("kubelet registered plugin %s", driver_name)
        return reg_pb.RegistrationStatusResponse()

    handlers = {
        "GetInfo": grpc.unary_unary_rpc_method_handler(
            get_info,
            request_deserializer=reg_pb.InfoRequest.FromString,
            response_serializer=reg_pb.PluginInfo.SerializeToString,
        ),
        "NotifyRegistrationStatus": grpc.unary_unary_rpc_method_handler(
            notify,
            request_deserializer=reg_pb.RegistrationStatus.FromString,
            response_serializer=reg_pb.RegistrationStatusResponse.SerializeToString,
        ),
    }
    return grpc.method_handlers_generic_handler(REGISTRATION_SERVICE, handlers)


class DraGrpcServer:
    """Serves the DRAPlugin + Registration + Health services."""

    def __init__(self, plugin, claims_client: ResourceClient,
                 driver_name: str, dra_address: str,
                 registration_address: Optional[str] = None):
        """``dra_address``/``registration_address`` are grpc bind targets
        (``unix:///path/dra.sock`` in production, ``localhost:0`` in
        tests). The registration response reports the dra socket's
        *filesystem path* (kubelet's dialing contract). The TCP health
        endpoint for kubelet's grpc probes is the separate
        SelfProbeHealthcheck (healthcheck.py), matching reference
        health.go."""
        self._plugin = plugin
        self._driver_name = driver_name
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        handlers = [
            _dra_handlers(plugin, claims_client, "v1"),
            _dra_handlers(plugin, claims_client, "v1beta1"),
            _health_handlers(self._plugin_healthy),
        ]
        # the device-health stream is served only when a health monitor
        # actually runs (DeviceHealthCheck gate on the TPU plugin) — an
        # unmonitored plugin must NOT advertise authoritative HEALTHY
        # verdicts; kubelet then falls back to its no-health-service
        # default (reference helper's conditional registration,
        # draplugin.go:623-627)
        self.supported_versions = list(SUPPORTED_VERSIONS)
        if getattr(plugin, "health", None) is not None:
            handlers.append(_dra_health_handlers(plugin))
            self.supported_versions.append(DRA_HEALTH_SERVICE)
        self._server.add_generic_rpc_handlers(tuple(handlers))
        self._reg_server = None
        # Socket files this instance owns. A cleanly-stopping instance must
        # remove them: during a rolling update (unique-per-pod socket
        # names, reference kubeletplugin RollingUpdate option) the NEW
        # instance cannot remove the old one's sockets, and a stale
        # registration socket would keep kubelet dialing a dead endpoint.
        self._socket_paths: List[str] = []
        self.dra_port = self._bind(self._server, dra_address)
        if dra_address.startswith("unix://"):
            self._socket_paths.append(dra_address[len("unix://"):])
        if registration_address is not None:
            endpoint_path = (dra_address[len("unix://"):]
                             if dra_address.startswith("unix://")
                             else dra_address)
            self._reg_server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
            self._reg_server.add_generic_rpc_handlers((
                _registration_handlers(
                    driver_name, endpoint_path,
                    supported_versions=self.supported_versions),
            ))
            self.registration_port = self._bind(self._reg_server,
                                                registration_address)
            if registration_address.startswith("unix://"):
                self._socket_paths.append(
                    registration_address[len("unix://"):])

    @staticmethod
    def _bind(server, address: str) -> int:
        """Bind, unlinking a stale unix socket file first. A SIGKILLed
        predecessor (crash-restart, the reference's pod-restart path)
        never ran its unlink-on-stop, and binding over the leftover file
        fails — worse, grpc reports that failure as port 0 and the server
        would come up serving NOTHING while kubelet dials a dead socket
        forever. Socket paths are per-instance (rolling updates use
        unique-per-pod names), so a file already at OUR path can only be
        a dead predecessor's."""
        if address.startswith("unix://"):
            try:
                os.unlink(address[len("unix://"):])
            except OSError:
                pass
        port = server.add_insecure_port(address)
        if port == 0:
            raise RuntimeError(f"failed to bind gRPC server to {address}")
        return port

    def _plugin_healthy(self) -> bool:
        if hasattr(self._plugin, "healthy"):
            return bool(self._plugin.healthy())
        return True

    def start(self) -> None:
        self._server.start()
        if self._reg_server is not None:
            self._reg_server.start()

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)
        if self._reg_server is not None:
            self._reg_server.stop(grace)
        for path in self._socket_paths:
            try:
                os.unlink(path)
            except OSError:
                pass


class DraGrpcClient:
    """Test/tooling client speaking the same wire protocol as kubelet.

    ``api_version`` selects which served DRAPlugin service to dial ("v1"
    default, matching a modern kubelet; "v1beta1" for the beta path) —
    both are served simultaneously by :class:`DraGrpcServer`."""

    def __init__(self, target: str, api_version: str = "v1"):
        self._channel = grpc.insecure_channel(target)
        self._pb = _DRA_PB[api_version]
        self._service = _DRA_SERVICE[api_version]

    def node_prepare_resources(self, claims: List[Dict]):
        req = self._pb.NodePrepareResourcesRequest()
        for c in claims:
            meta = c.get("metadata") or {}
            ref = req.claims.add()
            ref.uid = meta.get("uid", "")
            ref.namespace = meta.get("namespace", "")
            ref.name = meta.get("name", "")
        return self._channel.unary_unary(
            f"/{self._service}/NodePrepareResources",
            request_serializer=self._pb.NodePrepareResourcesRequest.SerializeToString,
            response_deserializer=self._pb.NodePrepareResourcesResponse.FromString,
        )(req)

    def node_unprepare_resources(self, refs: List[Dict]):
        req = self._pb.NodeUnprepareResourcesRequest()
        for c in refs:
            ref = req.claims.add()
            ref.uid = c.get("uid", "")
            ref.namespace = c.get("namespace", "")
            ref.name = c.get("name", "")
        return self._channel.unary_unary(
            f"/{self._service}/NodeUnprepareResources",
            request_serializer=self._pb.NodeUnprepareResourcesRequest.SerializeToString,
            response_deserializer=self._pb.NodeUnprepareResourcesResponse.FromString,
        )(req)

    def get_info(self, target: str) -> reg_pb.PluginInfo:
        channel = grpc.insecure_channel(target)
        return channel.unary_unary(
            f"/{REGISTRATION_SERVICE}/GetInfo",
            request_serializer=reg_pb.InfoRequest.SerializeToString,
            response_deserializer=reg_pb.PluginInfo.FromString,
        )(reg_pb.InfoRequest())

    def health_check(self) -> bool:
        resp = self._channel.unary_unary(
            f"/{HEALTH_SERVICE}/Check",
            request_serializer=health_pb.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb.HealthCheckResponse.FromString,
        )(health_pb.HealthCheckRequest(service=""))
        return resp.status == health_pb.HealthCheckResponse.SERVING

    def close(self) -> None:
        self._channel.close()
