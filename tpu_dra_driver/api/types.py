"""CRD types: ComputeDomain and ComputeDomainClique.

Reference analog: api/nvidia.com/resource/v1beta1/{computedomain.go:38-141,
computedomainclique.go:109-157}.

- ``ComputeDomain``: a workload-scoped, ephemeral multi-host ICI slice
  domain (the MNNVL/IMEX-domain analog). Spec: ``num_nodes``, the name of
  the workload ResourceClaimTemplate to stamp, and an allocation mode.
  Status: global Ready/NotReady plus per-node entries.
- ``ComputeDomainClique``: named ``<cdUID>.<cliqueID>`` where the clique id
  is the ICI-reachability group (for TPUs: the physical slice id reported
  by the device library). Holds the daemon membership list keyed by node
  name, through which per-node daemons rendezvous and receive stable
  worker indices.

Objects serialize to/from plain k8s-style dicts so they flow through the
generic in-memory API machinery (tpu_dra_driver.kube) and YAML templates.
"""

from __future__ import annotations

import copy
import time
import uuid as uuidlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra_driver import API_GROUP, API_VERSION

APIV = f"{API_GROUP}/{API_VERSION}"

# Max hosts per ComputeDomain. Reference: 18 nodes (GB200 IMEX domain
# limit, compute-domain-controller/main.go:55-59). TPU pod slices go far
# larger: a v5p pod is 960 hosts (8960 chips / 4 per host... nominal cap
# below is per-domain, conservative default, overridable by flag).
DEFAULT_MAX_NODES_PER_DOMAIN = 64

ALLOCATION_MODE_ALL = "All"
ALLOCATION_MODE_SINGLE = "Single"

STATUS_READY = "Ready"
STATUS_NOT_READY = "NotReady"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[Dict] = field(default_factory=list)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    generation: int = 0

    @staticmethod
    def new(name: str, namespace: str = "") -> "ObjectMeta":
        return ObjectMeta(
            name=name,
            namespace=namespace,
            uid=str(uuidlib.uuid4()),
            creation_timestamp=time.time(),
        )

    def to_obj(self) -> Dict:
        out: Dict = {"name": self.name}
        if self.namespace:
            out["namespace"] = self.namespace
        if self.uid:
            out["uid"] = self.uid
        if self.resource_version:
            out["resourceVersion"] = self.resource_version
        if self.labels:
            out["labels"] = dict(self.labels)
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        if self.finalizers:
            out["finalizers"] = list(self.finalizers)
        if self.owner_references:
            out["ownerReferences"] = copy.deepcopy(self.owner_references)
        if self.creation_timestamp:
            out["creationTimestamp"] = self.creation_timestamp
        if self.deletion_timestamp is not None:
            out["deletionTimestamp"] = self.deletion_timestamp
        if self.generation:
            out["generation"] = self.generation
        return out

    @staticmethod
    def from_obj(d: Dict) -> "ObjectMeta":
        return ObjectMeta(
            name=d.get("name", ""),
            namespace=d.get("namespace", ""),
            uid=d.get("uid", ""),
            resource_version=d.get("resourceVersion", ""),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            finalizers=list(d.get("finalizers") or []),
            owner_references=copy.deepcopy(d.get("ownerReferences") or []),
            creation_timestamp=d.get("creationTimestamp", 0.0),
            deletion_timestamp=d.get("deletionTimestamp"),
            generation=d.get("generation", 0),
        )


# ---------------------------------------------------------------------------
# ComputeDomain
# ---------------------------------------------------------------------------

@dataclass
class ComputeDomainChannelSpec:
    """Reference ComputeDomainChannelSpec (computedomain.go:93-101):
    allocationMode lives under spec.channel, enum All|Single, default
    Single — "All" requests every ICI channel, "Single" exactly one."""

    resource_claim_template_name: str = ""
    allocation_mode: str = ALLOCATION_MODE_SINGLE


@dataclass
class ComputeDomainSpec:
    num_nodes: int = 0
    # numSlices > 1 = a multislice domain: the CD spans that many ICI
    # slices (one clique each) stitched over DCN; workloads additionally
    # get MEGASCALE_* bootstrap env. TPU-native extension beyond the
    # reference (whose IMEX domain is always one fabric).
    num_slices: int = 1
    channel: ComputeDomainChannelSpec = field(default_factory=ComputeDomainChannelSpec)


@dataclass
class ComputeDomainNodeStatus:
    name: str = ""
    ip_address: str = ""
    clique_id: str = ""
    index: int = -1
    status: str = STATUS_NOT_READY


@dataclass
class ComputeDomainStatus:
    status: str = STATUS_NOT_READY
    nodes: List[ComputeDomainNodeStatus] = field(default_factory=list)


@dataclass
class ComputeDomain:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ComputeDomainSpec = field(default_factory=ComputeDomainSpec)
    status: ComputeDomainStatus = field(default_factory=ComputeDomainStatus)

    KIND = "ComputeDomain"
    PLURAL = "computedomains"

    def validate(self) -> None:
        # numNodes may be zero (reference computedomain.go:63-88: with the
        # DNSNames gate the workload tracks its own worker count and
        # numNodes only drives the global Ready status).
        if self.spec.num_nodes < 0:
            raise ValueError("spec.numNodes must be >= 0")
        if self.spec.num_slices < 1:
            raise ValueError("spec.numSlices must be >= 1")
        if (self.spec.num_slices > 1 and self.spec.num_nodes
                and self.spec.num_nodes % self.spec.num_slices):
            raise ValueError(
                f"spec.numNodes ({self.spec.num_nodes}) must be a multiple "
                f"of spec.numSlices ({self.spec.num_slices})")
        if not self.spec.channel.resource_claim_template_name:
            raise ValueError("spec.channel.resourceClaimTemplate.name must be set")
        if self.spec.channel.allocation_mode not in (
                ALLOCATION_MODE_ALL, ALLOCATION_MODE_SINGLE):
            raise ValueError(
                f"spec.channel.allocationMode must be {ALLOCATION_MODE_ALL!r} "
                f"or {ALLOCATION_MODE_SINGLE!r}"
            )

    def to_obj(self) -> Dict:
        return {
            "apiVersion": APIV,
            "kind": self.KIND,
            "metadata": self.metadata.to_obj(),
            "spec": {
                "numNodes": self.spec.num_nodes,
                "numSlices": self.spec.num_slices,
                "channel": {
                    "resourceClaimTemplate": {
                        "name": self.spec.channel.resource_claim_template_name,
                    },
                    "allocationMode": self.spec.channel.allocation_mode,
                },
            },
            "status": {
                "status": self.status.status,
                "nodes": [
                    {
                        "name": n.name,
                        "ipAddress": n.ip_address,
                        "cliqueID": n.clique_id,
                        "index": n.index,
                        "status": n.status,
                    }
                    for n in self.status.nodes
                ],
            },
        }

    @staticmethod
    def from_obj(d: Dict) -> "ComputeDomain":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return ComputeDomain(
            metadata=ObjectMeta.from_obj(d.get("metadata") or {}),
            spec=ComputeDomainSpec(
                num_nodes=spec.get("numNodes", 0),
                num_slices=spec.get("numSlices", 1),
                channel=ComputeDomainChannelSpec(
                    resource_claim_template_name=(
                        ((spec.get("channel") or {}).get("resourceClaimTemplate") or {})
                        .get("name", "")
                    ),
                    allocation_mode=(
                        (spec.get("channel") or {}).get(
                            "allocationMode",
                            # legacy location (pre-fix specs) at spec level
                            spec.get("allocationMode", ALLOCATION_MODE_SINGLE))
                    ),
                ),
            ),
            status=ComputeDomainStatus(
                status=status.get("status", STATUS_NOT_READY),
                nodes=[
                    ComputeDomainNodeStatus(
                        name=n.get("name", ""),
                        ip_address=n.get("ipAddress", ""),
                        clique_id=n.get("cliqueID", ""),
                        index=n.get("index", -1),
                        status=n.get("status", STATUS_NOT_READY),
                    )
                    for n in status.get("nodes") or []
                ],
            ),
        )


# ---------------------------------------------------------------------------
# ComputeDomainClique
# ---------------------------------------------------------------------------

@dataclass
class CliqueDaemon:
    """One per-node daemon's membership entry (list-map keyed by node_name,
    reference computedomainclique.go:109-157)."""

    node_name: str = ""
    ip_address: str = ""
    index: int = -1
    status: str = STATUS_NOT_READY


@dataclass
class ComputeDomainClique:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    daemons: List[CliqueDaemon] = field(default_factory=list)

    KIND = "ComputeDomainClique"
    PLURAL = "computedomaincliques"

    @staticmethod
    def clique_name(cd_uid: str, clique_id: str) -> str:
        """Cliques are named ``<cdUID>.<cliqueID>``."""
        return f"{cd_uid}.{clique_id}"

    def daemon_for(self, node_name: str) -> Optional[CliqueDaemon]:
        for d in self.daemons:
            if d.node_name == node_name:
                return d
        return None

    def to_obj(self) -> Dict:
        return {
            "apiVersion": APIV,
            "kind": self.KIND,
            "metadata": self.metadata.to_obj(),
            "daemons": [
                {
                    "nodeName": x.node_name,
                    "ipAddress": x.ip_address,
                    "index": x.index,
                    "status": x.status,
                }
                for x in self.daemons
            ],
        }

    @staticmethod
    def from_obj(d: Dict) -> "ComputeDomainClique":
        return ComputeDomainClique(
            metadata=ObjectMeta.from_obj(d.get("metadata") or {}),
            daemons=[
                CliqueDaemon(
                    node_name=x.get("nodeName", ""),
                    ip_address=x.get("ipAddress", ""),
                    index=x.get("index", -1),
                    status=x.get("status", STATUS_NOT_READY),
                )
                for x in d.get("daemons") or []
            ],
        )
