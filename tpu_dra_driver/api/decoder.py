"""Strict and non-strict decoders for opaque device configs.

Reference analog: api/nvidia.com/resource/v1beta1/api.go:46-98 — two scheme
decoders: **Strict** (rejects unknown fields; used on user input so typos
fail loudly at admission/prepare time) and **Nonstrict** (tolerates unknown
fields; used when re-reading checkpoints written by a newer/older version,
so up/downgrades don't brick recovery).
"""

from __future__ import annotations

from typing import Dict

from tpu_dra_driver import API_GROUP, API_VERSION
from tpu_dra_driver.api.configs import CONFIG_KINDS, _ConfigBase, _from_dict


class DecodeError(ValueError):
    pass


class Decoder:
    def __init__(self, strict: bool):
        self._strict = strict

    @property
    def strict(self) -> bool:
        return self._strict

    def decode(self, obj: Dict) -> _ConfigBase:
        """Decode a raw opaque-config object (already parsed JSON/YAML dict)."""
        if not isinstance(obj, dict):
            raise DecodeError(f"opaque config must be an object, got {type(obj).__name__}")
        apiv = obj.get("apiVersion", "")
        kind = obj.get("kind", "")
        if not apiv or not kind:
            raise DecodeError("opaque config missing apiVersion or kind")
        group, _, version = apiv.partition("/")
        if group != API_GROUP:
            raise DecodeError(
                f"unknown opaque config group {group!r} (expected {API_GROUP!r})"
            )
        if version != API_VERSION:
            raise DecodeError(
                f"unknown opaque config version {version!r} for group "
                f"{API_GROUP!r} (expected {API_VERSION!r})"
            )
        cls = CONFIG_KINDS.get(kind)
        if cls is None:
            raise DecodeError(
                f"unknown opaque config kind {kind!r} for group {API_GROUP!r}"
            )
        try:
            cfg = _from_dict(cls, obj, strict=self._strict)
        except KeyError as e:
            raise DecodeError(f"strict decode of {kind}: {e.args[0]}") from e
        except TypeError as e:
            raise DecodeError(f"decode of {kind}: {e}") from e
        return cfg

    def decode_validated(self, obj: Dict) -> _ConfigBase:
        """Decode + normalize + validate (the order the reference applies
        to every opaque config it accepts, api.go:41-44)."""
        cfg = self.decode(obj)
        try:
            cfg.normalize()
            cfg.validate()
        except (AttributeError, TypeError) as e:
            # Wrong-typed field values surface here (e.g. a string where an
            # object belongs) — keep them inside the decode-error taxonomy.
            raise DecodeError(f"malformed {obj.get('kind')}: {e}") from e
        return cfg


STRICT_DECODER = Decoder(strict=True)
NONSTRICT_DECODER = Decoder(strict=False)
