"""Opaque per-claim device configs with the Normalize/Validate contract.

Reference analog: api/nvidia.com/resource/v1beta1/{gpuconfig.go:28-89,
sharing.go:27-273, migconfig.go:27-77, vfiodeviceconfig.go:184-210,
computedomainconfig.go:27-86}. Every config is a runtime object with
``apiVersion``/``kind`` that implements ``normalize()`` (fill defaults)
and ``validate()`` (reject bad input).

TPU mapping:

- GpuConfig → :class:`TpuConfig` — sharing via time-slicing (runtime
  scheduler interval) or multi-process (multiple clients on one chip with
  per-client HBM limits; the MPS analog without a control daemon where
  possible).
- MigDeviceConfig → :class:`SubsliceConfig` — sharing on a sub-slice.
- VfioDeviceConfig → :class:`VfioTpuConfig` — empty marker selecting
  passthrough preparation.
- ComputeDomainChannelConfig / ComputeDomainDaemonConfig — carry the
  ``domain_id`` tying a claim to its ComputeDomain.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import ClassVar, Dict, Optional

from tpu_dra_driver import API_GROUP, API_VERSION

APIV = f"{API_GROUP}/{API_VERSION}"


class ValidationError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Sharing strategies
# ---------------------------------------------------------------------------

TIMESLICE_INTERVALS = ("Default", "Short", "Medium", "Long")

# Multi-process HBM limit bounds (percent of the chip's HBM one client may
# allocate; reference sharing.go enforces MPS thread%/pinned-mem bounds).
HBM_LIMIT_MIN_PERCENT = 1
HBM_LIMIT_MAX_PERCENT = 100
MAX_MULTI_PROCESS_CLIENTS = 16


@dataclass
class TimeSlicingConfig:
    interval: str = "Default"

    def normalize(self) -> None:
        if not self.interval:
            self.interval = "Default"

    def validate(self) -> None:
        if self.interval not in TIMESLICE_INTERVALS:
            raise ValidationError(
                f"unknown time-slice interval {self.interval!r}; "
                f"must be one of {TIMESLICE_INTERVALS}"
            )


@dataclass
class MultiProcessConfig:
    """Multiple processes share one chip; libtpu multi-client config.

    ``hbm_limit_percent`` bounds each client's HBM allocation;
    ``max_clients`` bounds concurrent processes.
    """

    max_clients: int = 0               # 0 → normalize to default
    hbm_limit_percent: Optional[int] = None

    DEFAULT_MAX_CLIENTS: ClassVar[int] = 4

    def normalize(self) -> None:
        if self.max_clients == 0:
            self.max_clients = self.DEFAULT_MAX_CLIENTS
        if self.hbm_limit_percent is None:
            self.hbm_limit_percent = 100 // self.max_clients

    def validate(self) -> None:
        if not (1 <= self.max_clients <= MAX_MULTI_PROCESS_CLIENTS):
            raise ValidationError(
                f"maxClients {self.max_clients} outside [1, {MAX_MULTI_PROCESS_CLIENTS}]"
            )
        if self.hbm_limit_percent is not None and not (
            HBM_LIMIT_MIN_PERCENT <= self.hbm_limit_percent <= HBM_LIMIT_MAX_PERCENT
        ):
            raise ValidationError(
                f"hbmLimitPercent {self.hbm_limit_percent} outside "
                f"[{HBM_LIMIT_MIN_PERCENT}, {HBM_LIMIT_MAX_PERCENT}]"
            )


SHARING_STRATEGIES = ("TimeSlicing", "MultiProcess")


@dataclass
class SharingConfig:
    strategy: str = "TimeSlicing"
    time_slicing: Optional[TimeSlicingConfig] = None
    multi_process: Optional[MultiProcessConfig] = None

    def normalize(self) -> None:
        if self.strategy == "TimeSlicing" and self.time_slicing is None:
            self.time_slicing = TimeSlicingConfig()
        if self.strategy == "MultiProcess" and self.multi_process is None:
            self.multi_process = MultiProcessConfig()
        if self.time_slicing:
            self.time_slicing.normalize()
        if self.multi_process:
            self.multi_process.normalize()

    def validate(self) -> None:
        if self.strategy not in SHARING_STRATEGIES:
            raise ValidationError(
                f"unknown sharing strategy {self.strategy!r}; "
                f"must be one of {SHARING_STRATEGIES}"
            )
        if self.strategy == "TimeSlicing":
            if self.multi_process is not None:
                raise ValidationError("multiProcess set but strategy is TimeSlicing")
            assert self.time_slicing is not None
            self.time_slicing.validate()
        else:
            if self.time_slicing is not None:
                raise ValidationError("timeSlicing set but strategy is MultiProcess")
            assert self.multi_process is not None
            self.multi_process.validate()


# ---------------------------------------------------------------------------
# Config objects (the opaque-parameter payloads)
# ---------------------------------------------------------------------------

@dataclass
class _ConfigBase:
    KIND: ClassVar[str] = ""

    def normalize(self) -> None:
        pass

    def validate(self) -> None:
        pass

    def to_obj(self) -> Dict:
        out = {"apiVersion": APIV, "kind": self.KIND}
        out.update(_to_camel_dict(self))
        return out


@dataclass
class TpuConfig(_ConfigBase):
    """Per-claim config for a full-chip (or dynamic sub-slice parent) device."""

    KIND: ClassVar[str] = "TpuConfig"
    sharing: Optional[SharingConfig] = None

    def normalize(self) -> None:
        if self.sharing is not None:
            self.sharing.normalize()

    def validate(self) -> None:
        if self.sharing is not None:
            self.sharing.validate()


@dataclass
class SubsliceConfig(_ConfigBase):
    """Per-claim config for a sub-slice device (MigDeviceConfig analog)."""

    KIND: ClassVar[str] = "SubsliceConfig"
    sharing: Optional[SharingConfig] = None

    def normalize(self) -> None:
        if self.sharing is not None:
            self.sharing.normalize()

    def validate(self) -> None:
        if self.sharing is not None:
            self.sharing.validate()
        # Multi-process on a sub-slice is allowed (like MPS-on-MIG); nothing
        # extra to check beyond the sharing config itself.


@dataclass
class VfioTpuConfig(_ConfigBase):
    """Empty marker config selecting vfio passthrough preparation
    (reference vfiodeviceconfig.go:184-210)."""

    KIND: ClassVar[str] = "VfioTpuConfig"


@dataclass
class ComputeDomainChannelConfig(_ConfigBase):
    """Ties a workload claim's channel device to a ComputeDomain.

    ``allocation_mode`` mirrors reference computedomainconfig.go:31 and
    device_state.go:474-485: the claim always allocates exactly one DRA
    channel device, but ``All`` makes Prepare inject *every* channel
    device node into the container."""

    KIND: ClassVar[str] = "ComputeDomainChannelConfig"
    domain_id: str = ""
    allocation_mode: str = ""

    ALLOCATION_MODES: ClassVar[tuple] = ("Single", "All")

    def normalize(self) -> None:
        if not self.allocation_mode:
            self.allocation_mode = "Single"

    def validate(self) -> None:
        if not isinstance(self.domain_id, str) or not self.domain_id:
            raise ValidationError("domainID must be a non-empty string")
        if self.allocation_mode not in self.ALLOCATION_MODES:
            raise ValidationError(
                f"allocationMode {self.allocation_mode!r} must be one of "
                f"{self.ALLOCATION_MODES}")


@dataclass
class ComputeDomainDaemonConfig(_ConfigBase):
    """Ties a daemon claim to a ComputeDomain."""

    KIND: ClassVar[str] = "ComputeDomainDaemonConfig"
    domain_id: str = ""

    def validate(self) -> None:
        if not isinstance(self.domain_id, str) or not self.domain_id:
            raise ValidationError("domainID must be a non-empty string")


CONFIG_KINDS = {
    c.KIND: c
    for c in (
        TpuConfig,
        SubsliceConfig,
        VfioTpuConfig,
        ComputeDomainChannelConfig,
        ComputeDomainDaemonConfig,
    )
}


# ---------------------------------------------------------------------------
# camelCase <-> snake_case plumbing (objects serialize k8s-style)
# ---------------------------------------------------------------------------

def _camel(s: str) -> str:
    parts = s.split("_")
    out = parts[0] + "".join(p.title() for p in parts[1:])
    # k8s convention: trailing "Id" renders as "ID"
    if out.endswith("Id"):
        out = out[:-2] + "ID"
    return out


def _snake(s: str) -> str:
    if s.endswith("ID"):
        s = s[:-2] + "Id"
    out = []
    for ch in s:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _to_camel_dict(obj) -> Dict:
    out = {}
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if v is None:
            continue
        if dataclasses.is_dataclass(v):
            v = _to_camel_dict(v)
        out[_camel(f.name)] = v
    return out


def _from_dict(cls, data: Dict, strict: bool, path: str = ""):
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in data.items():
        if k in ("apiVersion", "kind") and path == "":
            continue
        name = _snake(k)
        if name not in fields:
            if strict:
                raise KeyError(f"unknown field {path + k!r} for {cls.__name__}")
            continue
        sub = _NESTED.get((cls, name))
        if sub is not None and v is not None:
            if not isinstance(v, dict):
                raise TypeError(
                    f"field {path + k!r} must be an object, got {type(v).__name__}"
                )
            v = _from_dict(sub, v, strict, path=f"{path}{k}.")
        kwargs[name] = v
    return cls(**kwargs)


# nested dataclass fields that need recursive decoding
_NESTED = {
    (TpuConfig, "sharing"): SharingConfig,
    (SubsliceConfig, "sharing"): SharingConfig,
    (SharingConfig, "time_slicing"): TimeSlicingConfig,
    (SharingConfig, "multi_process"): MultiProcessConfig,
}
