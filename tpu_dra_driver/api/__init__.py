"""api — CRD types and opaque device-config types for resource.tpu.google.com/v1beta1.

Reference analog: api/nvidia.com/resource/v1beta1 — CRD types
(ComputeDomain, ComputeDomainClique), opaque configs (GpuConfig,
MigDeviceConfig, VfioDeviceConfig, ComputeDomainChannelConfig,
ComputeDomainDaemonConfig) with a Strict decoder for user input and a
Nonstrict decoder for checkpoint re-reads (api.go:46-98), and the
Normalize()/Validate() contract every config implements (api.go:41-44).
"""

from tpu_dra_driver.api.configs import (  # noqa: F401
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
    MultiProcessConfig,
    SubsliceConfig,
    TimeSlicingConfig,
    TpuConfig,
    VfioTpuConfig,
)
from tpu_dra_driver.api.decoder import (  # noqa: F401
    DecodeError,
    NONSTRICT_DECODER,
    STRICT_DECODER,
    Decoder,
)
from tpu_dra_driver.api.types import (  # noqa: F401
    ComputeDomain,
    ComputeDomainClique,
    ObjectMeta,
)
