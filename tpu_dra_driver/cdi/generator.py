"""TPU-native CDI (Container Device Interface) spec generation.

Reference analog: cmd/gpu-kubelet-plugin/cdi.go:65-304 — the reference
delegates to the NVIDIA Container Toolkit's nvcdi to compute driver-library
mounts/hooks and writes per-claim transient CDI specs under
``/var/run/cdi``. The TPU build needs **no toolkit**: a TPU container needs

- the device nodes (``/dev/accel*`` per claimed chip, or the vfio group
  node, or a sub-slice partition node),
- the libtpu shared library mounted from the host driver root,
- ``TPU_*`` bootstrap env (visible-chip list, topology of the claimed set,
  sharing limits, worker identity for ComputeDomains),
- optionally ``/dev/vfio/vfio`` + the group node for passthrough.

So the generator is self-contained here. Per-claim spec files are written
atomically (tmp + rename) and named ``<vendor>_claim-<uid>.json``; device
names inside a claim spec are claim-scoped so concurrent claims never
collide (mirrors claim-UID-scoped transient specs in the reference).

A small TTL cache keeps common edits cheap (reference cdi.go:125-182 uses a
5-minute TTL cache for GetCommonEdits / device specs because cold NVML
queries are O(seconds); our enumeration is cheap but the cache keeps the
Prepare hot path allocation-free).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra_driver.pkg import metrics as _metrics

CDI_VERSION = "0.6.0"
DEFAULT_CDI_ROOT = "/var/run/cdi"
# default vendor; each driver constructs its CdiHandler with its own vendor
# so the two kubelet plugins never collide on claim-spec filenames or
# qualified device names (the reference likewise uses one CDI vendor per
# driver name)
VENDOR = "tpu.google.com"
CLASS = "device"
KIND = f"{VENDOR}/{CLASS}"

DEFAULT_LIBTPU_HOST_PATH = "/home/kubernetes/bin/libtpu.so"
DEFAULT_LIBTPU_CONTAINER_PATH = "/lib/libtpu.so"

# Well-known libtpu locations, probed in order under the driver root
# (reference root.go:28-45 getDriverLibraryPath searches the standard
# library dirs for libnvidia-ml.so.1 the same way).
LIBTPU_SEARCH_PATHS = (
    "/home/kubernetes/bin/libtpu.so",          # GKE node image
    "/usr/lib/libtpu.so",
    "/usr/lib64/libtpu.so",
    "/usr/local/lib/libtpu.so",
    "/lib/libtpu.so",
    "/usr/lib/x86_64-linux-gnu/libtpu.so",
    "/usr/lib/aarch64-linux-gnu/libtpu.so",
)


def find_libtpu(driver_root: str = "/") -> Optional[str]:
    """First existing libtpu under the driver root, or None.

    Reference analog: root.findFile (root.go:82-96) — the driver may be
    installed on the host (driver_root "/") or via an installer container
    mounted at e.g. /driver-root.
    """
    root = driver_root.rstrip("/")
    for rel in LIBTPU_SEARCH_PATHS:
        cand = root + rel
        if os.path.isfile(cand):
            return cand
    return None


def dev_root_for(driver_root: str = "/") -> str:
    """Where this driver root's device nodes live (reference
    root.go:65-80 isDevRoot/getDevRoot): a root containing a /dev
    directory is a dev root; otherwise device nodes come from "/"."""
    root = driver_root.rstrip("/") or "/"
    if root != "/" and os.path.isdir(os.path.join(root, "dev")):
        return root
    return "/"


@dataclass
class ContainerEdits:
    """A subset of the CDI containerEdits schema the driver emits."""

    env: Dict[str, str] = field(default_factory=dict)
    device_nodes: List[Dict] = field(default_factory=list)
    mounts: List[Dict] = field(default_factory=list)
    hooks: List[Dict] = field(default_factory=list)

    def merge(self, other: "ContainerEdits") -> "ContainerEdits":
        out = ContainerEdits(
            env=dict(self.env),
            device_nodes=list(self.device_nodes),
            mounts=list(self.mounts),
            hooks=list(self.hooks),
        )
        out.env.update(other.env)
        seen_nodes = {d["path"] for d in out.device_nodes}
        out.device_nodes += [d for d in other.device_nodes
                             if d["path"] not in seen_nodes]
        seen_mounts = {m["containerPath"] for m in out.mounts}
        out.mounts += [m for m in other.mounts
                       if m["containerPath"] not in seen_mounts]
        out.hooks += other.hooks
        return out

    def to_obj(self) -> Dict:
        out: Dict = {}
        if self.env:
            out["env"] = [f"{k}={v}" for k, v in sorted(self.env.items())]
        if self.device_nodes:
            out["deviceNodes"] = self.device_nodes
        if self.mounts:
            out["mounts"] = self.mounts
        if self.hooks:
            out["hooks"] = self.hooks
        return out


@dataclass
class CdiDevice:
    """One named device entry in a claim spec."""

    name: str
    edits: ContainerEdits
    kind: str = KIND

    @property
    def qualified_name(self) -> str:
        return f"{self.kind}={self.name}"


@dataclass
class CdiSpec:
    devices: List[CdiDevice]
    common_edits: ContainerEdits
    kind: str = KIND

    def to_obj(self) -> Dict:
        return {
            "cdiVersion": CDI_VERSION,
            "kind": self.kind,
            "devices": [
                {"name": d.name, "containerEdits": d.edits.to_obj()}
                for d in self.devices
            ],
            "containerEdits": self.common_edits.to_obj(),
        }


class CdiHandler:
    def __init__(self, cdi_root: str = DEFAULT_CDI_ROOT,
                 driver_root: str = "/",
                 libtpu_host_path: str = DEFAULT_LIBTPU_HOST_PATH,
                 libtpu_container_path: str = DEFAULT_LIBTPU_CONTAINER_PATH,
                 driver_version: str = "",
                 common_edits_ttl: float = 300.0,
                 vendor: str = VENDOR):
        self.vendor = vendor
        self.kind = f"{vendor}/{CLASS}"
        self._cdi_root = cdi_root
        self._driver_root = driver_root.rstrip("/") or "/"
        self._libtpu_host = libtpu_host_path
        self._libtpu_container = libtpu_container_path
        self._driver_version = driver_version
        self._ttl = common_edits_ttl
        self._mu = threading.Lock()
        self._common_cache: Optional[tuple[float, ContainerEdits]] = None
        # content-keyed render cache: claims with the same device SHAPE
        # (device set + edits + merged common edits) differ only by the
        # claim UID woven into device names, so the rendered JSON is
        # cached once as a UID-placeholder template and re-stamped per
        # claim — identical shapes skip serialization entirely
        self._render_cache: OrderedDict[str, str] = OrderedDict()
        self._render_cache_max = 256

    # -- common edits -------------------------------------------------------

    def get_common_edits(self) -> ContainerEdits:
        """Edits every TPU container gets regardless of which device:
        libtpu mount + driver-version env (reference: driver lib mounts,
        nvidia-cdi-hook ldcache update — unnecessary for libtpu's single
        dlopen'd .so)."""
        with self._mu:
            now = time.monotonic()
            if self._common_cache and now - self._common_cache[0] < self._ttl:
                return self._common_cache[1]
            # Prefer a probed well-known location under the driver root;
            # fall back to the configured path (which may not exist yet —
            # the prestart init container waits for the installer).
            host_lib = find_libtpu(self._driver_root)
            if host_lib is None:
                host_lib = self._libtpu_host
                if self._driver_root != "/":
                    host_lib = self._driver_root + host_lib
            edits = ContainerEdits(
                env={
                    "TPU_DRIVER_VERSION": self._driver_version or "unknown",
                    "TPU_LIBRARY_PATH": self._libtpu_container,
                },
                mounts=[{
                    "hostPath": host_lib,
                    "containerPath": self._libtpu_container,
                    "options": ["ro", "nosuid", "nodev", "bind"],
                }],
            )
            self._common_cache = (now, edits)
            return edits

    def invalidate_cache(self) -> None:
        with self._mu:
            self._common_cache = None
            # common edits feed every rendered claim spec: a stale
            # template must not outlive the inputs it rendered from
            self._render_cache.clear()

    # -- claim specs --------------------------------------------------------

    def claim_spec_path(self, claim_uid: str) -> str:
        return os.path.join(self._cdi_root, f"{self.vendor}_claim-{claim_uid}.json")

    @staticmethod
    def claim_device_name(claim_uid: str, canonical_name: str) -> str:
        return f"claim-{claim_uid}-{canonical_name}"

    def write_claim_spec(self, claim_uid: str, devices: List[CdiDevice],
                         extra_common: Optional[ContainerEdits] = None) -> List[str]:
        """Write the per-claim transient spec atomically; returns the
        qualified CDI ids kubelet passes to the runtime."""
        body, qualified = self.render_claim_spec(claim_uid, devices,
                                                 extra_common=extra_common)
        self.write_claim_spec_body(claim_uid, body)
        return qualified

    def render_claim_spec(self, claim_uid: str, devices: List[CdiDevice],
                          extra_common: Optional[ContainerEdits] = None):
        """Render (via the shape-keyed cache) without touching disk;
        returns ``(body, qualified_ids)`` so a caller can choose its own
        durability contract for the file write."""
        common = self.get_common_edits()
        if extra_common is not None:
            common = common.merge(extra_common)
        body = self._render_body(claim_uid, devices, common)
        return body, [f"{self.kind}={d.name}" for d in devices]

    def write_claim_spec_body(self, claim_uid: str, body: str,
                              durable: bool = True) -> None:
        """Atomic (tmp + rename) spec-file write. ``durable=False`` skips
        the per-file fsync — only valid when the caller persists ``body``
        through its own fsynced store (the journal checkpoint) and
        restores the file from it on recovery, so the spec survives power
        loss without paying a per-claim fsync on the prepare path."""
        os.makedirs(self._cdi_root, exist_ok=True)
        path = self.claim_spec_path(claim_uid)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(body)
            f.flush()
            if durable:
                os.fsync(f.fileno())
        os.replace(tmp, path)

    def restore_claim_spec(self, claim_uid: str, body: str) -> bool:
        """Recovery-side companion of the non-durable write: if the
        on-disk spec file is missing or diverges from the checkpointed
        body (torn by power loss before the page cache flushed), rewrite
        it durably. Returns True when a rewrite happened."""
        try:
            with open(self.claim_spec_path(claim_uid)) as f:
                if f.read() == body:
                    return False
        except OSError:
            pass
        self.write_claim_spec_body(claim_uid, body, durable=True)
        _metrics.CDI_SPECS_RESTORED.inc()
        return True

    #: placeholder the render cache stores instead of the claim UID (a
    #: template is shape-keyed, so it must be UID-free to be reusable)
    _UID_TOKEN = "__CLAIM_UID__"

    def _render_body(self, claim_uid: str, devices: List[CdiDevice],
                     common: ContainerEdits) -> str:
        """Serialize the claim spec, via the content-keyed render cache:
        the key digests (device set, per-device edits, merged common
        edits) with the claim UID normalized out, so identical shapes —
        e.g. a serving tier preparing hundreds of one-seat claims —
        reuse one rendered template and pay only a UID re-stamp."""
        shape = json.dumps({
            "devices": [{"name": d.name.replace(claim_uid, self._UID_TOKEN),
                         "edits": d.edits.to_obj()} for d in devices],
            "common": common.to_obj(),
            "kind": self.kind,
        }, sort_keys=True)
        key = hashlib.sha256(shape.encode()).hexdigest()
        with self._mu:
            template = self._render_cache.get(key)
            if template is not None:
                self._render_cache.move_to_end(key)
        if template is None:
            _metrics.CDI_RENDER_CACHE_MISSES.inc()
            spec = CdiSpec(
                devices=[CdiDevice(name=d.name, edits=d.edits,
                                   kind=self.kind) for d in devices],
                common_edits=common, kind=self.kind)
            rendered = json.dumps(spec.to_obj(), indent=2,
                                  sort_keys=True) + "\n"
            template = rendered.replace(claim_uid, self._UID_TOKEN)
            with self._mu:
                self._render_cache[key] = template
                self._render_cache.move_to_end(key)
                while len(self._render_cache) > self._render_cache_max:
                    self._render_cache.popitem(last=False)
        else:
            _metrics.CDI_RENDER_CACHE_HITS.inc()
        return template.replace(self._UID_TOKEN, claim_uid)

    def delete_claim_spec(self, claim_uid: str) -> None:
        try:
            os.remove(self.claim_spec_path(claim_uid))
        except FileNotFoundError:
            pass

    def read_claim_spec(self, claim_uid: str) -> Optional[Dict]:
        try:
            with open(self.claim_spec_path(claim_uid)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
