"""CDI 0.7 spec-file validation.

The e2e bar for "the runtime will accept our spec" without a live
containerd: every claim spec the driver writes is checked against the
CDI 0.7 object model (cncf-tags/container-device-interface SPEC.md —
the same structure the reference's nvcdi emits and containerd's CDI
cache parses). Field set mirrors
tags.cncf.io/container-device-interface/specs-go/config.go.
"""

from __future__ import annotations

import re
from typing import Dict

import jsonschema

# vendor: dns-style; class: alphanumeric with - and _
_KIND_RE = re.compile(
    r"^[a-zA-Z0-9]([-a-zA-Z0-9.]*[a-zA-Z0-9])?/[a-zA-Z0-9]([-_a-zA-Z0-9]*[a-zA-Z0-9])?$")
_DEVICE_NAME_RE = re.compile(r"^[a-zA-Z0-9]([-_.:a-zA-Z0-9]*[a-zA-Z0-9])?$")
_ENV_RE = re.compile(r"^[^=]+=.*$", re.S)

# CDI released versions a 0.7-era runtime accepts (containerd's cdi cache
# via the CDI Go library's validator).
SUPPORTED_CDI_VERSIONS = ("0.3.0", "0.4.0", "0.5.0", "0.6.0", "0.7.0")

_CONTAINER_EDITS_SCHEMA = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "env": {"type": "array", "items": {"type": "string"}},
        "deviceNodes": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["path"],
                "additionalProperties": False,
                "properties": {
                    "path": {"type": "string", "minLength": 1},
                    "hostPath": {"type": "string"},
                    "type": {"enum": ["b", "c", "u", "p", ""]},
                    "major": {"type": "integer"},
                    "minor": {"type": "integer"},
                    "fileMode": {"type": "integer"},
                    "permissions": {"type": "string",
                                    "pattern": "^[rwm]*$"},
                    "uid": {"type": "integer", "minimum": 0},
                    "gid": {"type": "integer", "minimum": 0},
                },
            },
        },
        "hooks": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["hookName", "path"],
                "additionalProperties": False,
                "properties": {
                    "hookName": {"enum": [
                        "prestart", "createRuntime", "createContainer",
                        "startContainer", "poststart", "poststop"]},
                    "path": {"type": "string", "minLength": 1},
                    "args": {"type": "array", "items": {"type": "string"}},
                    "env": {"type": "array", "items": {"type": "string"}},
                    "timeout": {"type": "integer"},
                },
            },
        },
        "mounts": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["hostPath", "containerPath"],
                "additionalProperties": False,
                "properties": {
                    "hostPath": {"type": "string", "minLength": 1},
                    "containerPath": {"type": "string", "minLength": 1},
                    "options": {"type": "array", "items": {"type": "string"}},
                    "type": {"type": "string"},
                },
            },
        },
        "intelRdt": {"type": "object"},
        "additionalGIDs": {
            "type": "array",
            "items": {"type": "integer", "minimum": 0},
        },
    },
}

CDI_SPEC_SCHEMA = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["cdiVersion", "kind", "devices"],
    "additionalProperties": False,
    "properties": {
        "cdiVersion": {"enum": list(SUPPORTED_CDI_VERSIONS)},
        "kind": {"type": "string"},
        "annotations": {"type": "object",
                        "additionalProperties": {"type": "string"}},
        "devices": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["name", "containerEdits"],
                "additionalProperties": False,
                "properties": {
                    "name": {"type": "string"},
                    "annotations": {
                        "type": "object",
                        "additionalProperties": {"type": "string"}},
                    "containerEdits": _CONTAINER_EDITS_SCHEMA,
                },
            },
        },
        "containerEdits": _CONTAINER_EDITS_SCHEMA,
    },
}


class CdiValidationError(ValueError):
    pass


def validate_spec(spec: Dict) -> None:
    """Raise CdiValidationError when ``spec`` would be rejected by a CDI
    0.7 runtime parser; returns None on success."""
    try:
        jsonschema.validate(spec, CDI_SPEC_SCHEMA)
    except jsonschema.ValidationError as e:
        raise CdiValidationError(
            f"CDI spec invalid at {'/'.join(str(p) for p in e.absolute_path)}: "
            f"{e.message}") from e
    if not _KIND_RE.match(spec["kind"]):
        raise CdiValidationError(f"invalid CDI kind {spec['kind']!r}")
    seen = set()
    for dev in spec["devices"]:
        name = dev["name"]
        if not _DEVICE_NAME_RE.match(name):
            raise CdiValidationError(f"invalid device name {name!r}")
        if name in seen:
            raise CdiValidationError(f"duplicate device name {name!r}")
        seen.add(name)
    for edits in [spec.get("containerEdits", {})] + \
            [d["containerEdits"] for d in spec["devices"]]:
        for env in edits.get("env") or []:
            if not _ENV_RE.match(env):
                raise CdiValidationError(f"malformed env entry {env!r}")


def validate_file(path: str) -> Dict:
    """Validate a spec file on disk; returns the parsed spec."""
    import json
    with open(path) as f:
        spec = json.load(f)
    validate_spec(spec)
    return spec
