from tpu_dra_driver.cdi.generator import CdiHandler, CdiSpec  # noqa: F401
