"""compute-domain-kubelet-plugin binary
(reference analog: cmd/compute-domain-kubelet-plugin/main.go)."""

from __future__ import annotations

import signal
import sys
import threading
from typing import List, Optional

from tpu_dra_driver.pkg import faultinject
from tpu_dra_driver import COMPUTE_DOMAIN_DRIVER_NAME
from tpu_dra_driver.common import dump_config, install_stack_dump_handler
from tpu_dra_driver.computedomain.plugin.driver import (
    CdKubeletPlugin,
    CdKubeletPluginConfig,
)
from tpu_dra_driver.grpc_api.server import DraGrpcServer
from tpu_dra_driver.pkg.flags import (
    EnvArgumentParser,
    add_common_flags,
    config_dict,
    parse_http_endpoint,
    setup_observability,
)
from tpu_dra_driver.cmd.tpu_kubelet_plugin import make_clients, make_lib


def build_parser() -> EnvArgumentParser:
    p = EnvArgumentParser(prog="compute-domain-kubelet-plugin")
    add_common_flags(p)
    p.add_argument("--node-name", env="NODE_NAME", default="")
    p.add_argument("--state-dir", env="STATE_DIR",
                   default="/var/lib/kubelet/plugins/compute-domain.tpu.google.com")
    p.add_argument("--cdi-root", env="CDI_ROOT", default="/var/run/cdi")
    p.add_argument("--hosts-file-dir", env="HOSTS_FILE_DIR",
                   default="/run/tpu-dra")
    p.add_argument("--prepare-budget", env="PREPARE_BUDGET", type=float,
                   default=45.0)
    p.add_argument("--plugin-registry", env="PLUGIN_REGISTRY",
                   default="/var/lib/kubelet/plugins_registry")
    p.add_argument("--device-backend", env="DEVICE_BACKEND", default="native",
                   choices=["native", "fake"])
    p.add_argument("--accelerator-type", env="TPU_ACCELERATOR_TYPE", default="")
    p.add_argument("--health-port", env="HEALTH_PORT", type=int, default=51516)
    p.add_argument("--rolling-update-uid", env="POD_UID", default="",
                   help="pod UID (downward API); unique-per-instance "
                        "socket names for gap-free DaemonSet rolling "
                        "updates (kubelet >= 1.33)")
    p.add_argument("--http-endpoint", env="HTTP_ENDPOINT", default="",
                   help="host:port for /metrics, /healthz, /readyz, "
                        "/debug/threads and /debug/traces; empty disables")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    setup_observability(args, "compute-domain-kubelet-plugin")
    # chaos drills script faults into production binaries via
    # TPU_DRA_FAULTS (see docs/chaos.md); a no-op when unset
    faultinject.arm_from_env()
    install_stack_dump_handler()
    dump_config("compute-domain-kubelet-plugin", config_dict(args))
    if not args.node_name:
        print("--node-name/NODE_NAME is required", file=sys.stderr)
        return 2

    clients = make_clients(args)
    lib = make_lib(args)
    plugin = CdKubeletPlugin(clients, lib, CdKubeletPluginConfig(
        node_name=args.node_name, state_dir=args.state_dir,
        cdi_root=args.cdi_root, hosts_file_dir=args.hosts_file_dir,
        prepare_budget=args.prepare_budget))
    plugin.start()

    uid_part = (f"-{args.rolling_update_uid}" if args.rolling_update_uid
                else "")
    dra_sock = f"unix://{args.state_dir}/dra{uid_part}.sock"
    reg_sock = (f"unix://{args.plugin_registry}/"
                f"{COMPUTE_DOMAIN_DRIVER_NAME}{uid_part}-reg.sock")
    server = DraGrpcServer(
        plugin, clients.resource_claims, COMPUTE_DOMAIN_DRIVER_NAME,
        dra_address=dra_sock, registration_address=reg_sock)
    server.start()

    # Self-probing healthcheck on TCP for gRPC startup/liveness probes
    # (reference health.go, shared by both kubelet plugins).
    healthcheck = None
    if args.health_port >= 0:
        from tpu_dra_driver.grpc_api.healthcheck import SelfProbeHealthcheck
        healthcheck = SelfProbeHealthcheck(
            registration_target=reg_sock, dra_target=dra_sock,
            port=args.health_port,
            healthy_fn=getattr(plugin, "healthy", None))
        healthcheck.start()

    from tpu_dra_driver.pkg import slo
    slo.attach_recorder(plugin.event_recorder,
                        {"kind": "Node", "name": args.node_name})

    debug_server = None
    address = parse_http_endpoint(args.http_endpoint)
    if address is not None:
        from tpu_dra_driver.pkg.flags import debug_vars_fn
        from tpu_dra_driver.pkg.metrics import DebugHTTPServer
        debug_server = DebugHTTPServer(
            address, ready_check=plugin.healthy,
            json_endpoints={"/debug/vars": debug_vars_fn(
                args, "compute-domain-kubelet-plugin")})
        debug_server.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    if debug_server is not None:
        debug_server.stop()
    if healthcheck is not None:
        healthcheck.stop()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
