"""tpu-dra-doctor binary: one-command cluster diagnostics bundle.

The ``nvidia-bug-report.sh``/must-gather analog for this driver: point
it at every component's ``--http-endpoint`` (and optionally a
kubeconfig + plugin state dirs), and it collects all debug surfaces
into one tarball, runs automated findings (breaker open, SLO burning,
parked claims, shard imbalance, watch-mux lag, quarantined
checkpoints), and prints a severity-sorted triage summary.

    tpu-dra-doctor \\
        --endpoint allocation-controller=10.0.0.1:8080 \\
        --endpoint tpu-plugin-node0=10.0.1.2:8080 \\
        --state-dir node0=/var/lib/kubelet/plugins/tpu.google.com \\
        --kubeconfig ~/.kube/config \\
        --output /tmp/tpu-dra-doctor.tar.gz
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

from tpu_dra_driver.cmd.tpu_kubelet_plugin import make_clients
from tpu_dra_driver.pkg.flags import (
    EnvArgumentParser,
    add_common_flags,
    setup_observability,
)
from tpu_dra_driver.tools import doctor


def _parse_pairs(values: List[str], flag: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for item in values or []:
        name, sep, value = item.partition("=")
        if not sep or not name or not value:
            raise SystemExit(f"{flag}: expected NAME=VALUE, got {item!r}")
        out[name] = value
    return out


def build_parser() -> EnvArgumentParser:
    p = EnvArgumentParser(prog="tpu-dra-doctor")
    add_common_flags(p)
    p.add_argument("--endpoint", action="append", default=[],
                   metavar="NAME=HOST:PORT",
                   help="a component's --http-endpoint to collect from "
                        "(repeatable)")
    p.add_argument("--state-dir", action="append", default=[],
                   metavar="NAME=PATH",
                   help="a plugin state dir to inventory for checkpoints "
                        "and quarantined corpses (repeatable)")
    p.add_argument("--collect-events", action="store_true", default=False,
                   help="also collect recent Events through the API "
                        "server (--kubeconfig / in-cluster config)")
    p.add_argument("--output", env="DOCTOR_OUTPUT", default="",
                   help="bundle tarball path (default "
                        "./tpu-dra-doctor-<unix>.tar.gz)")
    p.add_argument("--timeout", type=float, default=3.0,
                   help="per-surface HTTP timeout in seconds")
    p.add_argument("--resample", type=float, default=0.0,
                   help="seconds between two /metrics samples per "
                        "component (0 disables); arms rate-shaped "
                        "findings like LEASE_FLAPPING to distinguish "
                        "ongoing churn from lifetime totals")
    p.add_argument("--fail-on", default="never",
                   choices=["never", "critical", "warning"],
                   help="exit nonzero when findings at/above this "
                        "severity exist (for scripted health gates)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    # a diagnostics CLI must not itself spawn an SLO engine thread
    args.slo_tick = 0.0
    setup_observability(args, "tpu-dra-doctor")

    endpoints = _parse_pairs(args.endpoint, "--endpoint")
    state_dirs = _parse_pairs(args.state_dir, "--state-dir")
    if not endpoints and not state_dirs:
        print("nothing to collect: pass at least one --endpoint or "
              "--state-dir", file=sys.stderr)
        return 2

    clients = None
    if args.collect_events:
        clients = make_clients(args)

    bundle = doctor.collect(endpoints, state_dirs=state_dirs,
                            clients=clients, timeout=args.timeout,
                            resample_after=args.resample)
    findings = doctor.run_findings(bundle)
    out_path = args.output or f"tpu-dra-doctor-{int(time.time())}.tar.gz"
    doctor.write_bundle(bundle, findings, out_path)

    print(doctor.summary_text(findings, bundle), end="")
    print(f"bundle written to {out_path}")

    if args.fail_on != "never":
        levels = {"critical": (doctor.CRITICAL,),
                  "warning": (doctor.CRITICAL, doctor.WARNING)}[args.fail_on]
        if any(f.severity in levels for f in findings):
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
