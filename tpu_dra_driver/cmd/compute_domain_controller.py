"""compute-domain-controller binary
(reference analog: cmd/compute-domain-controller/main.go:52-267).

Optional leader election (main.go:269-370); with it enabled the controller
machinery starts only while holding the lease.
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import List, Optional

from tpu_dra_driver.pkg import faultinject
from tpu_dra_driver.common import dump_config, install_stack_dump_handler
from tpu_dra_driver.computedomain.controller.controller import (
    ComputeDomainController,
    ControllerConfig,
)
from tpu_dra_driver.kube.leaderelection import LeaderElectionConfig, LeaderElector
from tpu_dra_driver.pkg.metrics import DebugHTTPServer
from tpu_dra_driver.pkg.flags import (
    EnvArgumentParser,
    add_common_flags,
    config_dict,
    parse_http_endpoint,
    setup_observability,
)
from tpu_dra_driver.cmd.tpu_kubelet_plugin import make_clients


def build_parser() -> EnvArgumentParser:
    p = EnvArgumentParser(prog="compute-domain-controller")
    add_common_flags(p)
    p.add_argument("--max-nodes-per-domain", env="MAX_NODES_PER_DOMAIN",
                   type=int, default=64)
    p.add_argument("--status-sync-interval", env="STATUS_SYNC_INTERVAL",
                   type=float, default=30.0,
                   help="status RESYNC BACKSTOP interval; convergence is "
                        "informer event-driven, this periodic pass only "
                        "heals missed watch events (was the 2 s poll "
                        "period before the event-driven rendezvous)")
    p.add_argument("--status-debounce", env="STATUS_DEBOUNCE",
                   type=float, default=0.01,
                   help="trailing debounce before an event-triggered "
                        "per-CD status sync runs; a burst of daemon joins "
                        "coalesces into one status write")
    p.add_argument("--workers", env="CONTROLLER_WORKERS", type=int,
                   default=2,
                   help="workqueue worker threads (reconciles and per-CD "
                        "status syncs for distinct CDs run in parallel)")
    p.add_argument("--leader-election", env="LEADER_ELECTION",
                   action="store_true", default=False)
    p.add_argument("--device-backend", env="DEVICE_BACKEND", default="native",
                   choices=["native", "fake"],
                   help="backend the stamped CD daemon pods run against")
    p.add_argument("--driver-image", env="DRIVER_IMAGE", default="",
                   help="image for stamped CD daemon pods (defaults to "
                        "this controller's own image in the chart)")
    p.add_argument("--daemon-log-verbosity", env="DAEMON_LOG_VERBOSITY",
                   type=int, default=4,
                   help="verbosity plumbed into stamped CD daemon pods "
                        "(reference daemonset.go:206-217)")
    p.add_argument("--daemon-log-format", env="DAEMON_LOG_FORMAT",
                   default="text", choices=["text", "json"],
                   help="log format plumbed into stamped CD daemon pods")
    p.add_argument("--daemon-http-endpoint", env="DAEMON_HTTP_ENDPOINT",
                   default="",
                   help="--http-endpoint plumbed into stamped CD daemon "
                        "pods so their /metrics + /debug/traces are "
                        "scrapeable (hostNetwork: pick the port cluster-"
                        "wide); empty keeps it disabled")
    p.add_argument("--additional-namespaces", env="ADDITIONAL_NAMESPACES",
                   default="",
                   help="comma-separated extra namespaces where the driver "
                        "may manage CD DaemonSets (reference "
                        "main.go --additional-namespaces)")
    p.add_argument("--leader-election-namespace",
                   env="LEADER_ELECTION_NAMESPACE", default="tpu-dra-driver")
    p.add_argument("--identity", env="POD_NAME", default="controller")
    p.add_argument("--http-endpoint", env="HTTP_ENDPOINT", default="",
                   help="host:port for /metrics, /healthz, /readyz and "
                        "/debug/threads (reference main.go:372-419); "
                        "empty disables the endpoint")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    setup_observability(args, "compute-domain-controller")
    # chaos drills script faults into production binaries via
    # TPU_DRA_FAULTS (see docs/chaos.md); a no-op when unset
    faultinject.arm_from_env()
    install_stack_dump_handler()
    dump_config("compute-domain-controller", config_dict(args))

    clients = make_clients(args)
    controller = ComputeDomainController(clients, ControllerConfig(
        max_nodes_per_domain=args.max_nodes_per_domain,
        status_sync_interval=args.status_sync_interval,
        status_debounce=args.status_debounce,
        workers=args.workers,
        device_backend=args.device_backend,
        daemon_image=args.driver_image,
        daemon_log_verbosity=args.daemon_log_verbosity,
        daemon_log_format=args.daemon_log_format,
        daemon_http_endpoint=args.daemon_http_endpoint,
        additional_namespaces=[ns.strip() for ns in
                               args.additional_namespaces.split(",")
                               if ns.strip()]))

    # the CD controller's per-instance registry carries
    # dra_cd_rendezvous_seconds — make it visible to the SLO engine's
    # cd-rendezvous-latency spec, and wire SLOBurnRate Events
    from tpu_dra_driver.pkg import slo
    slo.add_registry(controller.registry)
    slo.attach_recorder(controller.event_recorder,
                        {"kind": "Pod", "name": args.identity,
                         "namespace": args.leader_election_namespace})

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    debug_server = None
    address = parse_http_endpoint(args.http_endpoint)
    if address is not None:
        from tpu_dra_driver.pkg.flags import debug_vars_fn
        debug_server = DebugHTTPServer(
            address, registry=controller.registry,
            json_endpoints={"/debug/vars": debug_vars_fn(
                args, "compute-domain-controller")})
        debug_server.start()

    if args.leader_election:
        elector = LeaderElector(
            clients.leases,
            LeaderElectionConfig(identity=args.identity,
                                 namespace=args.leader_election_namespace),
            on_started_leading=controller.start,
            on_stopped_leading=controller.stop)
        elector.start()
        stop.wait()
        elector.stop()
    else:
        controller.start()
        stop.wait()
        controller.stop()
    if debug_server is not None:
        debug_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
