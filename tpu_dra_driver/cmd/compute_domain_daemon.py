"""compute-domain-daemon binary
(reference analog: cmd/compute-domain-daemon/main.go).

Subcommands:
- (default) run the daemon: join clique, maintain hosts mapping, report
  readiness; exit nonzero on fatal ICI fabric errors so Kubernetes
  restarts the pod (CrashOnICIFabricErrors).
- ``check``: readiness probe (reference main.go:425-451) — exits 0 iff
  the local daemon state says Ready (all clique peers resolvable).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
from typing import List, Optional

from tpu_dra_driver.pkg import faultinject
from tpu_dra_driver.common import dump_config, install_stack_dump_handler
from tpu_dra_driver.computedomain.daemon.daemon import (
    ComputeDomainDaemon,
    DaemonConfig,
)
from tpu_dra_driver.pkg.flags import (
    EnvArgumentParser,
    add_common_flags,
    config_dict,
    parse_gates,
    parse_http_endpoint,
    setup_observability,
)
from tpu_dra_driver.cmd.tpu_kubelet_plugin import make_clients, make_lib

READY_FILE = "ready"


def cd_run_dir(base: str, cd_uid: str) -> str:
    """Per-ComputeDomain subdirectory of the node-shared hostPath run dir.

    The base dir is one hostPath shared by every CD daemon pod on the node
    (and it survives pod restarts), so all daemon state — hosts mapping,
    worker-env snapshot, ready marker — must be scoped by CD UID or two
    domains on one node would read each other's files."""
    return os.path.join(base, cd_uid) if cd_uid else base


def build_parser() -> EnvArgumentParser:
    p = EnvArgumentParser(prog="compute-domain-daemon")
    p.add_argument("subcommand", nargs="?", default="run",
                   choices=["run", "check"])
    add_common_flags(p)
    p.add_argument("--compute-domain-uid", env="CD_UID", default="")
    p.add_argument("--compute-domain-name", env="CD_NAME", default="")
    p.add_argument("--compute-domain-namespace", env="CD_NAMESPACE", default="")
    p.add_argument("--node-name", env="NODE_NAME", default="")
    p.add_argument("--pod-name", env="POD_NAME", default="")
    p.add_argument("--pod-ip", env="POD_IP", default="")
    p.add_argument("--run-dir", env="RUN_DIR", default="/run/tpu-dra")
    p.add_argument("--state-dir", env="STATE_DIR",
                   default="/var/lib/tpu-dra-driver")
    p.add_argument("--device-backend", env="DEVICE_BACKEND", default="native",
                   choices=["native", "fake"])
    p.add_argument("--accelerator-type", env="TPU_ACCELERATOR_TYPE", default="")
    p.add_argument("--http-endpoint", env="HTTP_ENDPOINT", default="",
                   help="host:port for /metrics (informer/watch families, "
                        "dra_swallowed_errors_total), /healthz, /readyz "
                        "(clique readiness), /debug/threads and "
                        "/debug/traces; empty disables — without it the "
                        "daemon's metrics are unscrapeable")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.subcommand == "check":
        # The probe path must be cheap and API-free: the running daemon
        # maintains a ready marker file alongside its worker-env rendering.
        ready_path = os.path.join(
            cd_run_dir(args.run_dir, args.compute_domain_uid), READY_FILE)
        return 0 if os.path.exists(ready_path) else 1

    setup_observability(args, "compute-domain-daemon")
    # chaos drills script faults into production binaries via
    # TPU_DRA_FAULTS (see docs/chaos.md); a no-op when unset
    faultinject.arm_from_env()
    install_stack_dump_handler()
    dump_config("compute-domain-daemon", config_dict(args))
    for req in ("compute_domain_uid", "node_name", "pod_ip"):
        if not getattr(args, req):
            print(f"--{req.replace('_','-')} is required", file=sys.stderr)
            return 2

    run_dir = cd_run_dir(args.run_dir, args.compute_domain_uid)
    ready_path = os.path.join(run_dir, READY_FILE)
    # A stale marker from a previous incarnation (the dir is a hostPath
    # that survives crashes) must never satisfy probes before *this*
    # daemon reaches Ready.
    try:
        os.remove(ready_path)
    except OSError:
        pass

    clients = make_clients(args)
    lib = make_lib(args)
    daemon = ComputeDomainDaemon(clients, lib, DaemonConfig(
        cd_uid=args.compute_domain_uid, cd_name=args.compute_domain_name,
        cd_namespace=args.compute_domain_namespace,
        node_name=args.node_name, pod_name=args.pod_name, pod_ip=args.pod_ip,
        hosts_file=os.path.join(run_dir, "hosts"),
        worker_env_file=os.path.join(run_dir, "worker-env.json"),
        # graceful stop removes the whole per-CD dir (the hostPath
        # outlives the pod; see DaemonConfig.run_dir). run_dir here is
        # always CD-scoped: --compute-domain-uid is required above, so
        # cd_run_dir returned base/<uid>, never the shared base.
        run_dir=run_dir,
        gates=parse_gates(args)))
    daemon.start()

    debug_server = None
    address = parse_http_endpoint(args.http_endpoint)
    if address is not None:
        from tpu_dra_driver.pkg.flags import debug_vars_fn
        from tpu_dra_driver.pkg.metrics import DebugHTTPServer
        debug_server = DebugHTTPServer(
            address, ready_check=daemon.check,
            json_endpoints={"/debug/vars": debug_vars_fn(
                args, "compute-domain-daemon")})
        debug_server.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    def maintain_ready_marker():
        while not stop.wait(1.0):
            try:
                if daemon.check():
                    with open(ready_path, "w") as f:
                        f.write("ok\n")
                elif os.path.exists(ready_path):
                    os.remove(ready_path)
            except OSError:
                pass

    threading.Thread(target=maintain_ready_marker, daemon=True,
                     name="ready-marker").start()

    # block until shutdown or a fatal fabric error (exit nonzero → restart)
    while not stop.is_set():
        if daemon.fatal.wait(timeout=0.5):
            daemon.stop()
            if debug_server is not None:
                debug_server.stop()
            try:
                os.remove(ready_path)
            except OSError:
                pass
            print("fatal ICI fabric error; exiting for pod restart",
                  file=sys.stderr)
            return 1
    daemon.stop()
    if debug_server is not None:
        debug_server.stop()
    try:
        os.remove(ready_path)
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
