"""tpu-kubelet-plugin binary (reference analog: cmd/gpu-kubelet-plugin/main.go).

Startup order mirrors driver.go:66-173: device lib → device state (with
startup sub-slice sweep) → gRPC registration with kubelet → health
monitor → checkpoint cleanup → ResourceSlice publishing.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from typing import List, Optional

from tpu_dra_driver.pkg import faultinject
from tpu_dra_driver import DRIVER_NAME
from tpu_dra_driver.common import dump_config, install_stack_dump_handler
from tpu_dra_driver.grpc_api.server import DraGrpcServer
from tpu_dra_driver.kube.client import ClientSets
from tpu_dra_driver.pkg.flags import (
    EnvArgumentParser,
    add_common_flags,
    config_dict,
    parse_gates,
    setup_observability,
)
from tpu_dra_driver.plugin.driver import PluginConfig, TpuKubeletPlugin


def build_parser() -> EnvArgumentParser:
    p = EnvArgumentParser(prog="tpu-kubelet-plugin")
    add_common_flags(p)
    p.add_argument("--node-name", env="NODE_NAME", required=False, default="")
    p.add_argument("--state-dir", env="STATE_DIR",
                   default="/var/lib/kubelet/plugins/tpu.google.com")
    p.add_argument("--cdi-root", env="CDI_ROOT", default="/var/run/cdi")
    p.add_argument("--driver-root", env="DRIVER_ROOT", default="/")
    p.add_argument("--slice-layout", env="SLICE_LAYOUT", default="combined",
                   choices=["combined", "split"])
    p.add_argument("--max-devices-per-slice", env="MAX_DEVICES_PER_SLICE",
                   type=int, default=0,
                   help="split combined-layout device lists over multiple "
                        "slices above this many devices (stable slice-name "
                        "assignment keeps a one-device change local to one "
                        "slice); 0 publishes one combined slice")
    p.add_argument("--plugin-registry", env="PLUGIN_REGISTRY",
                   default="/var/lib/kubelet/plugins_registry")
    p.add_argument("--device-backend", env="DEVICE_BACKEND", default="native",
                   choices=["native", "fake"],
                   help="fake runs hardware-free (demo/CI)")
    p.add_argument("--accelerator-type", env="TPU_ACCELERATOR_TYPE", default="")
    p.add_argument("--health-port", env="HEALTH_PORT", type=int, default=51515)
    p.add_argument("--rolling-update-uid", env="POD_UID", default="",
                   help="pod UID (downward API); when set, socket names "
                        "are unique per instance so a DaemonSet rolling "
                        "update never drops registration (reference "
                        "kubeletplugin RollingUpdate; kubelet >= 1.33)")
    p.add_argument("--http-endpoint", env="HTTP_ENDPOINT", default="",
                   help="host:port for /metrics (dra_claim_* histograms), "
                        "/healthz and /debug/threads; empty disables")
    return p


def make_lib(args):
    if args.device_backend == "fake":
        from tpu_dra_driver.tpulib.fake import FakeSystemConfig, FakeTpuLib
        # Per-node identity for hardware-free multi-host runs (the sim e2e
        # suite, demo kind clusters): a real deployment derives these from
        # the hardware/metadata server; fake mode takes them from the pod
        # env the way the DaemonSet's downward API feeds NODE_NAME.
        return FakeTpuLib(FakeSystemConfig(
            accelerator_type=args.accelerator_type or "v5p-8",
            host_index=int(os.environ.get("FAKE_TPU_HOST_INDEX") or 0),
            slice_id=os.environ.get("FAKE_TPU_SLICE_ID") or None))
    from tpu_dra_driver.tpulib.native import NativeSystemConfig, NativeTpuLib
    # binaries without a --state-dir flag (the CD daemon) share the
    # node-global native state dir
    state_dir = getattr(args, "state_dir", "/var/lib/tpu-dra-driver")
    return NativeTpuLib(NativeSystemConfig(
        state_dir=f"{state_dir}/native",
        accelerator_type=args.accelerator_type or None))


def make_clients(args) -> ClientSets:
    if getattr(args, "kube_backend", "rest") == "fake":
        return ClientSets()  # in-memory FakeCluster (hardware-free mode)
    from tpu_dra_driver.kube.rest import RestCluster, RestClusterConfig
    cfg = (RestClusterConfig.from_kubeconfig(args.kubeconfig)
           if args.kubeconfig else RestClusterConfig.auto())
    return ClientSets(cluster=RestCluster(cfg))


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    setup_observability(args, "tpu-kubelet-plugin")
    # chaos drills script faults into production binaries via
    # TPU_DRA_FAULTS (see docs/chaos.md); a no-op when unset
    faultinject.arm_from_env()
    install_stack_dump_handler()
    dump_config("tpu-kubelet-plugin", config_dict(args))
    if not args.node_name:
        print("--node-name/NODE_NAME is required", file=sys.stderr)
        return 2

    clients = make_clients(args)
    lib = make_lib(args)
    plugin = TpuKubeletPlugin(clients, lib, PluginConfig(
        node_name=args.node_name, state_dir=args.state_dir,
        cdi_root=args.cdi_root, driver_root=args.driver_root,
        slice_layout=args.slice_layout, gates=parse_gates(args),
        max_devices_per_slice=args.max_devices_per_slice))
    plugin.start()

    # Rolling update: unique-per-instance socket names (dra-<uid>.sock /
    # <driver>-<uid>-reg.sock, the reference helper's exact naming,
    # draplugin.go:560-574) let old and new DaemonSet pods serve
    # simultaneously; kubelet registers both and the prepare window never
    # gaps. Cross-instance safety comes from the node-global pu.lock/
    # cp.lock flocks the prepare path already takes (the serialize.lock
    # analog).
    uid_part = (f"-{args.rolling_update_uid}" if args.rolling_update_uid
                else "")
    dra_sock = f"unix://{args.state_dir}/dra{uid_part}.sock"
    reg_sock = (f"unix://{args.plugin_registry}/"
                f"{DRIVER_NAME}{uid_part}-reg.sock")
    server = DraGrpcServer(plugin, clients.resource_claims, DRIVER_NAME,
                           dra_address=dra_sock,
                           registration_address=reg_sock)
    server.start()

    # Dedicated healthcheck service for the container's gRPC startup/
    # liveness probes: self-probes both unix sockets end-to-end per Check
    # (reference health.go:51-149). --health-port < 0 disables.
    healthcheck = None
    if args.health_port >= 0:
        from tpu_dra_driver.grpc_api.healthcheck import SelfProbeHealthcheck
        healthcheck = SelfProbeHealthcheck(
            registration_target=reg_sock, dra_target=dra_sock,
            port=args.health_port, healthy_fn=plugin.healthy)
        healthcheck.start()

    # SLOBurnRate Events ride the plugin's existing recorder, hung off
    # this Node (the object an operator describes when a node is slow)
    from tpu_dra_driver.pkg import slo
    slo.attach_recorder(plugin.event_recorder,
                        {"kind": "Node", "name": args.node_name})

    debug_server = None
    from tpu_dra_driver.pkg.flags import debug_vars_fn, parse_http_endpoint
    address = parse_http_endpoint(args.http_endpoint)
    if address is not None:
        from tpu_dra_driver.pkg.metrics import DebugHTTPServer
        debug_server = DebugHTTPServer(
            address, ready_check=plugin.healthy,
            json_endpoints={
                "/debug/vars": debug_vars_fn(args, "tpu-kubelet-plugin")})
        debug_server.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    if debug_server is not None:
        debug_server.stop()
    if healthcheck is not None:
        healthcheck.stop()
    server.stop()
    plugin.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
