"""webhook binary (reference analog: cmd/webhook/main.go)."""

from __future__ import annotations

import signal
import sys
import threading
from typing import List, Optional

from tpu_dra_driver.pkg import faultinject
from tpu_dra_driver.common import dump_config, install_stack_dump_handler
from tpu_dra_driver.pkg.flags import (
    EnvArgumentParser,
    add_common_flags,
    config_dict,
    parse_http_endpoint,
    setup_observability,
)
from tpu_dra_driver.webhook.server import WebhookServer


def build_parser() -> EnvArgumentParser:
    p = EnvArgumentParser(prog="tpu-dra-webhook")
    add_common_flags(p)
    p.add_argument("--bind", env="WEBHOOK_BIND", default="0.0.0.0")
    p.add_argument("--port", env="WEBHOOK_PORT", type=int, default=8443)
    p.add_argument("--tls-cert", env="WEBHOOK_TLS_CERT", default="")
    p.add_argument("--tls-key", env="WEBHOOK_TLS_KEY", default="")
    p.add_argument("--http-endpoint", env="HTTP_ENDPOINT", default="",
                   help="host:port for the plaintext /metrics, /healthz, "
                        "/readyz and /debug/threads endpoint (separate "
                        "from the HTTPS admission port); empty disables")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    setup_observability(args, "tpu-dra-webhook")
    # chaos drills script faults into production binaries via
    # TPU_DRA_FAULTS (see docs/chaos.md); a no-op when unset
    faultinject.arm_from_env()
    install_stack_dump_handler()
    dump_config("tpu-dra-webhook", config_dict(args))
    server = WebhookServer(args.bind, args.port,
                           cert_file=args.tls_cert or None,
                           key_file=args.tls_key or None)
    server.start()
    debug_server = None
    address = parse_http_endpoint(args.http_endpoint)
    if address is not None:
        from tpu_dra_driver.pkg.flags import debug_vars_fn
        from tpu_dra_driver.pkg.metrics import DebugHTTPServer
        debug_server = DebugHTTPServer(
            address,
            json_endpoints={"/debug/vars": debug_vars_fn(
                args, "tpu-dra-webhook")})
        debug_server.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    if debug_server is not None:
        debug_server.stop()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
