"""allocation-controller binary: the in-repo scheduler role at scale.

Real clusters let kube-scheduler's structured-parameters allocator place
claims; hardware-free clusters (the sim e2e suite, kind demo clusters
without a DRA-aware scheduler build) need the same role as a deployable
component. This binary runs the event-driven
:class:`~tpu_dra_driver.kube.allocation_controller.AllocationController`:
informer-fed device catalog + usage ledger, pending claims drained in
batches by ``--allocator-workers`` workers through one snapshot per
batch.
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import List, Optional

from tpu_dra_driver import DRIVER_NAME
from tpu_dra_driver.common import dump_config, install_stack_dump_handler
from tpu_dra_driver.cmd.tpu_kubelet_plugin import make_clients
from tpu_dra_driver.kube.allocation_controller import (
    AllocationController,
    AllocationControllerConfig,
)
from tpu_dra_driver.kube.catalog import DEFAULT_INDEX_ATTRIBUTES
from tpu_dra_driver.pkg import faultinject
from tpu_dra_driver.pkg.flags import (
    EnvArgumentParser,
    add_common_flags,
    config_dict,
    parse_http_endpoint,
    setup_observability,
)


def build_parser() -> EnvArgumentParser:
    p = EnvArgumentParser(prog="allocation-controller")
    add_common_flags(p)
    p.add_argument("--driver-name", env="ALLOCATOR_DRIVER_NAME",
                   default=DRIVER_NAME,
                   help="DRA driver whose ResourceSlices this allocator "
                        "serves")
    p.add_argument("--allocator-workers", env="ALLOCATOR_WORKERS",
                   type=int, default=2,
                   help="worker threads draining the pending-claim queue "
                        "(parallel batches; ledger reservations keep them "
                        "conflict-free)")
    p.add_argument("--allocator-batch", env="ALLOCATOR_BATCH",
                   type=int, default=64,
                   help="max claims allocated against one catalog+usage "
                        "snapshot per batch")
    p.add_argument("--index-attributes", env="ALLOCATOR_INDEX_ATTRIBUTES",
                   default=",".join(DEFAULT_INDEX_ATTRIBUTES),
                   help="comma-separated attribute names the device "
                        "catalog maintains equality indexes over")
    p.add_argument("--http-endpoint", env="HTTP_ENDPOINT", default="",
                   help="host:port for /metrics (dra_allocator_*, "
                        "dra_allocation_seconds), /healthz and "
                        "/debug/threads; empty disables")
    p.add_argument("--leader-election", env="LEADER_ELECTION",
                   action="store_true", default=False,
                   help="lease-based leader election; REQUIRED when "
                        "running more than one UNSHARDED replica — the "
                        "ledger's reservations only coordinate workers "
                        "inside one process, and verify-on-commit only "
                        "catches conflicting writers of the SAME claim, "
                        "so two concurrent allocators could hand one "
                        "device to two different claims. With "
                        "--allocator-shards, per-slot leases replace "
                        "this global lease")
    p.add_argument("--allocator-shards", env="ALLOCATOR_SHARDS",
                   type=int, default=0,
                   help="shard the control plane over N consistent-hash "
                        "slots (0 = unsharded). Replicas compete for a "
                        "lease PER SLOT and drain only claims whose "
                        "candidate pools hash to slots they own — "
                        "conflict-free scale-out instead of one global "
                        "leader (docs/allocator.md)")
    p.add_argument("--shard-ring-seed", env="SHARD_RING_SEED",
                   type=int, default=0,
                   help="seed of the rendezvous hash ring; MUST be "
                        "identical across all replicas")
    p.add_argument("--leader-election-namespace",
                   env="LEADER_ELECTION_NAMESPACE", default="tpu-dra-driver")
    p.add_argument("--identity", env="POD_NAME", default="allocator")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    setup_observability(args, "allocation-controller")
    faultinject.arm_from_env()
    install_stack_dump_handler()
    dump_config("allocation-controller", config_dict(args))

    clients = make_clients(args)
    index_attributes = tuple(
        a.strip() for a in args.index_attributes.split(",") if a.strip())
    config = AllocationControllerConfig(
        driver_name=args.driver_name,
        workers=args.allocator_workers,
        batch_max=args.allocator_batch,
        index_attributes=index_attributes)
    shard_wiring = None
    if args.allocator_shards > 0:
        from tpu_dra_driver.kube.sharding import ShardRing, shard_slots
        from tpu_dra_driver.kube.allocation_controller import ShardWiring
        shard_wiring = ShardWiring(
            ShardRing(shard_slots(args.allocator_shards),
                      seed=args.shard_ring_seed),
            owned=set())
    controller = AllocationController(clients, config, shard=shard_wiring,
                                      identity=args.identity)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    debug_server = None
    address = parse_http_endpoint(args.http_endpoint)
    if address is not None:
        from tpu_dra_driver.pkg.flags import debug_vars_fn
        from tpu_dra_driver.pkg.metrics import DebugHTTPServer
        debug_server = DebugHTTPServer(
            address, ready_check=lambda: controller.claim_informer.synced,
            json_endpoints={
                "/debug/vars": debug_vars_fn(args, "allocation-controller"),
                # parked-claim UIDs + owned shard slots for the doctor
                "/debug/allocator": controller.debug_state,
            })
        debug_server.start()

    from tpu_dra_driver.kube.events import EventRecorder
    recorder = EventRecorder(clients.events,
                             component="allocation-controller",
                             host=args.identity)
    from tpu_dra_driver.pkg import slo
    slo.attach_recorder(recorder,
                        {"kind": "Pod", "name": args.identity,
                         "namespace": args.leader_election_namespace})
    if shard_wiring is not None:
        # One leader PER SHARD SLOT: the controller starts with nothing
        # owned and drains whatever slots its leases win; a replica
        # death expires its slots and survivors take over (hand-off).
        from tpu_dra_driver.kube.fencing import FencingTokens
        from tpu_dra_driver.kube.sharding import (
            ShardLeaseConfig,
            ShardLeaseManager,
        )
        manager = ShardLeaseManager(
            clients.leases, shard_wiring.ring.members,
            ShardLeaseConfig(namespace=args.leader_election_namespace,
                             identity=args.identity),
            on_slots_changed=controller.set_owned_slots,
            recorder=recorder)
        # Epoch fencing: stamp every allocation-plane write with the
        # held slot epochs; the pre-commit lease re-read (verify_reads)
        # is the client-side guard for clusters without the fake's
        # fencing admission hook. A rejected write demotes this replica
        # (resign every lease, rejoin) instead of double-allocating.
        controller.set_fencing(
            FencingTokens(shard_wiring.ring, manager.slot_epoch,
                          leases=clients.leases,
                          namespace=args.leader_election_namespace,
                          verify_reads=True),
            on_stale_writer=lambda reason: manager.resign_all())
        controller.start()
        manager.start()
        stop.wait()
        manager.stop()
        controller.stop()
    elif args.leader_election:
        from tpu_dra_driver.kube.leaderelection import (
            LeaderElectionConfig,
            LeaderElector,
        )
        elector = LeaderElector(
            clients.leases,
            LeaderElectionConfig(identity=args.identity,
                                 namespace=args.leader_election_namespace,
                                 lease_name="allocation-controller"),
            on_started_leading=controller.start,
            on_stopped_leading=controller.stop,
            recorder=recorder)
        elector.start()
        stop.wait()
        elector.stop()
    else:
        controller.start()
        stop.wait()
        controller.stop()
    if debug_server is not None:
        debug_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
