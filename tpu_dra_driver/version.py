"""Version string plumbed from the build.

Reference analog: internal/info/version.go (build-flag stamped version).
"""

VERSION = "0.1.0"


def version_string() -> str:
    return f"tpu-dra-driver {VERSION}"
