"""Operator tooling built on the driver's debug surfaces — currently
the ``tpu-dra-doctor`` must-gather/triage library (doctor.py), driven
by the :mod:`tpu_dra_driver.cmd.doctor` CLI."""
