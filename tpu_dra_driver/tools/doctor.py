"""tpu-dra-doctor: one-command cluster diagnostics bundle + triage.

Reference analog: ``nvidia-bug-report.sh`` / the k8s ``must-gather``
pattern — when a fleet misbehaves, the first ask is always "collect
everything and send it over". This module is the collection AND the
first read: it pulls every observability surface this driver exposes
(``/metrics``, ``/debug/traces``, ``/debug/slo``,
``/debug/criticalpath``, ``/debug/vars``, ``/debug/allocator``,
``/debug/explain``, ``/debug/timeseries``) from
every component endpoint, plus checkpoint state dirs and recent
Kubernetes Events, into one tarball — then runs automated findings
over the bundle (breaker open, SLO burning, parked claims with
per-reason breakdowns, shard imbalance, watch-mux lag, commit-phase
stalls, quarantined checkpoints, evicted traces) and
prints a severity-sorted triage summary, so the operator starts from
"here is what is wrong" instead of from raw text exposition.

The CLI lives in :mod:`tpu_dra_driver.cmd.doctor`; the sim e2e suite
(tests/e2e/run_e2e_sim.py, phase ``doctor``) exercises the whole loop
against production subprocesses.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: debug paths collected per component endpoint (artifact key -> path).
ENDPOINT_PATHS = {
    "metrics": "/metrics",
    "slo": "/debug/slo",
    "traces": "/debug/traces",
    "criticalpath": "/debug/criticalpath",
    "vars": "/debug/vars",
    "allocator": "/debug/allocator",
    "explain": "/debug/explain",
    "timeseries": "/debug/timeseries",
}

CRITICAL = "critical"
WARNING = "warning"
INFO = "info"
_SEVERITY_ORDER = {CRITICAL: 0, WARNING: 1, INFO: 2}

#: watch-mux p99 lag beyond this is an event-plane health finding.
MUX_LAG_P99_THRESHOLD_S = 1.0

#: leadership transitions at-or-above this within the resample window
#: (or, without a resample, in the whole scrape) flag LEASE_FLAPPING —
#: a healthy fleet transitions once per hand-off, not continuously.
LEASE_FLAP_DELTA_THRESHOLD = 4
LEASE_FLAP_ABSOLUTE_THRESHOLD = 20

#: per-dimension growth within the --resample window at-or-above which
#: LEAK_SUSPECTED fires — long-horizon decay one-shot scrapes can't see
#: (the gauge families; checkpoint-dir byte growth has its own floor
#: because one in-flight prepare legitimately grows the file a little).
LEAK_GAUGE_DELTAS = {
    "dra_watch_streams_active": 2.0,
    "dra_allocator_parked_claims": 2.0,
}
LEAK_STATE_DIR_BYTES_THRESHOLD = 4096

#: a commit sub-phase whose p99 reaches this flags COMMIT_STALL — the
#: whole-commit SLO budget is sub-second, so one phase eating a quarter
#: second of it names the concrete perf target.
COMMIT_STALL_P99_THRESHOLD_S = 0.25

#: trailing window (seconds) the time-series-ring trend fits cover when
#: a component exposes /debug/timeseries (replaces the sleep-based
#: two-point --resample delta for that component).
TREND_WINDOW_S = 120.0

#: journal records past this flag JOURNAL_BLOAT — mirrors the plugin's
#: own compaction trigger (plugin/checkpoint.py
#: JOURNAL_COMPACT_MAX_RECORDS): a healthy writer compacts before the
#: journal ever reaches it, so a bundle catching it above means the
#: compactor is stalled or erroring.
JOURNAL_BLOAT_RECORDS_THRESHOLD = 512


@dataclass
class Finding:
    severity: str
    code: str
    component: str
    message: str
    details: Dict = field(default_factory=dict)

    def to_dict(self) -> Dict:
        return {"severity": self.severity, "code": self.code,
                "component": self.component, "message": self.message,
                "details": self.details}


# ---------------------------------------------------------------------------
# Prometheus text parsing (the doctor reads scrapes offline, so it needs
# its own reader for the 0.0.4 format pkg/metrics.py writes)
# ---------------------------------------------------------------------------


def _parse_labels(body: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    key = ""
    i = 0
    n = len(body)
    while i < n:
        j = body.index("=", i)
        key = body[i:j]
        assert body[j + 1] == '"'
        k = j + 2
        val = []
        while body[k] != '"':
            if body[k] == "\\":
                nxt = body[k + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                k += 2
            else:
                val.append(body[k])
                k += 1
        out[key] = "".join(val)
        i = k + 1
        if i < n and body[i] == ",":
            i += 1
    return out


def parse_metrics_text(text: str
                       ) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """``name -> [(labels, value), ...]`` from a 0.0.4 text scrape.
    Histogram series keep their ``_bucket``/``_sum``/``_count``
    suffixed names. Malformed lines are skipped — a doctor must read
    what it can, not crash on what it can't."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                body, tail = rest.rsplit("}", 1)
                labels = _parse_labels(body)
                value = float(tail.split()[0])
            else:
                parts = line.split()
                name, labels, value = parts[0], {}, float(parts[1])
        except (ValueError, IndexError, AssertionError):
            continue
        out.setdefault(name, []).append((labels, value))
    return out


def metric_value(samples: Dict, name: str,
                 labels: Optional[Dict[str, str]] = None) -> float:
    """Sum of a family's samples matching the given label subset."""
    total = 0.0
    for sample_labels, value in samples.get(name, []):
        if labels and any(sample_labels.get(k) != v
                          for k, v in labels.items()):
            continue
        total += value
    return total


def histogram_quantile(samples: Dict, family: str, q: float
                       ) -> Optional[float]:
    """Upper-bound estimate of quantile ``q`` from ``family``'s
    cumulative buckets (summed across label sets): the smallest bucket
    bound holding at least q of the observations. None without data."""
    total = metric_value(samples, f"{family}_count")
    if total <= 0:
        return None
    cum: Dict[float, float] = {}
    for labels, value in samples.get(f"{family}_bucket", []):
        le = labels.get("le", "")
        bound = float("inf") if le == "+Inf" else float(le)
        cum[bound] = cum.get(bound, 0.0) + value
    for bound in sorted(cum):
        if cum[bound] >= q * total:
            return bound
    return float("inf")


def histogram_quantile_by(samples: Dict, family: str, q: float,
                          label: str) -> Dict[str, float]:
    """Per-label-value quantile upper bounds for ``family`` — what
    :func:`histogram_quantile` cannot answer, because it sums label
    sets (the COMMIT_STALL finding needs p99 PER commit phase, not of
    the blended family)."""
    counts: Dict[str, float] = {}
    for labels, value in samples.get(f"{family}_count", []):
        lv = labels.get(label, "")
        counts[lv] = counts.get(lv, 0.0) + value
    out: Dict[str, float] = {}
    for lv, total in counts.items():
        if total <= 0:
            continue
        cum: Dict[float, float] = {}
        for labels, value in samples.get(f"{family}_bucket", []):
            if labels.get(label, "") != lv:
                continue
            le = labels.get("le", "")
            bound = float("inf") if le == "+Inf" else float(le)
            cum[bound] = cum.get(bound, 0.0) + value
        for bound in sorted(cum):
            if cum[bound] >= q * total:
                out[lv] = bound
                break
    return out


# ---------------------------------------------------------------------------
# time-series ring reads (/debug/timeseries artifacts)
# ---------------------------------------------------------------------------


def _has_timeseries(art: Dict) -> bool:
    """True when the component's ring is armed AND already holds a
    usable delta window (>= 2 points on some series)."""
    ts = art.get("timeseries") or {}
    return bool(ts.get("enabled")) and any(
        len(points) >= 2 for points in (ts.get("series") or {}).values())


def timeseries_delta(art: Dict, family: str,
                     window_s: float = TREND_WINDOW_S) -> Optional[float]:
    """Growth of ``family`` over the trailing window of the component's
    time-series ring, summed across label sets (raw series only —
    recording-rule series like ``:rate`` are skipped). None when the
    ring is absent or holds no usable points for the family."""
    ts = art.get("timeseries") or {}
    if not ts.get("enabled"):
        return None
    total: Optional[float] = None
    for key, points in (ts.get("series") or {}).items():
        if key.split("{", 1)[0] != family or len(points) < 2:
            continue
        t_last, v_last = points[-1]
        cutoff = t_last - window_s
        v_first = next((v for t, v in points if t >= cutoff), None)
        if v_first is None:
            continue
        total = (total or 0.0) + (v_last - v_first)
    return total


def timeseries_slope(art: Dict, family: str) -> Optional[float]:
    """Least-squares per-second trend of ``family``'s raw series
    (summed across label sets) — the fit that tells monotone growth
    from a step that already settled. None without usable data."""
    from tpu_dra_driver.pkg.metrics import least_squares_slope
    ts = art.get("timeseries") or {}
    if not ts.get("enabled"):
        return None
    total: Optional[float] = None
    for key, points in (ts.get("series") or {}).items():
        if key.split("{", 1)[0] != family:
            continue
        s = least_squares_slope([(t, v) for t, v in points])
        if s is not None:
            total = (total or 0.0) + s
    return total


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------


def _http_get(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def collect_endpoint(host_port: str, timeout: float = 3.0) -> Dict:
    """Every debug surface of one component. Unreachable/absent paths
    land under ``errors`` instead of failing the whole gather — a
    must-gather that dies on the sickest component is useless."""
    art: Dict = {"endpoint": host_port, "errors": {}}
    for key, path in ENDPOINT_PATHS.items():
        try:
            body = _http_get(f"http://{host_port}{path}", timeout)
            art[key] = body if key == "metrics" else json.loads(body)
        except Exception as e:  # noqa: BLE001 — recorded per-surface
            art["errors"][key] = f"{type(e).__name__}: {e}"
    return art


def resample_metrics(host_port: str, art: Dict, timeout: float) -> None:
    """Take the second /metrics sample (``metrics_resample``) for an
    already-collected component artifact, so rate-shaped findings
    (LEASE_FLAPPING) can distinguish ongoing churn from lifetime
    totals. :func:`collect` sleeps ONCE across the whole fleet and then
    resamples everyone — one shared wall-clock delta window."""
    if "metrics" not in art:
        return
    try:
        art["metrics_resample"] = _http_get(
            f"http://{host_port}/metrics", timeout)
    except Exception as e:  # noqa: BLE001 — recorded per-surface
        art["errors"]["metrics_resample"] = f"{type(e).__name__}: {e}"


def _read_json(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001 — best-effort offline read
        return None


def _checkpoint_claims(obj: Dict) -> Optional[Dict[str, Dict]]:
    """Raw claim-entry objects from a checkpoint envelope (v2 preferred,
    v1 fallback; checksums are NOT verified — the doctor reads what it
    can). None when no version parses."""
    for version in ("v2", "v1"):
        payload = obj.get(version)
        if not isinstance(payload, dict):
            continue
        return {uid: entry for uid, entry in
                (payload.get("claims") or {}).items()
                if isinstance(entry, dict)}
    return None


def _owned_devices(claims: Dict[str, Dict]) -> List[str]:
    """Canonical device names PrepareCompleted entries own."""
    names: List[str] = []
    for entry in claims.values():
        # v1 records only completed claims (no state field)
        if entry.get("state", "PrepareCompleted") != "PrepareCompleted":
            continue
        for dev in entry.get("preparedDevices") or []:
            if isinstance(dev, dict) and dev.get("canonicalName"):
                names.append(dev["canonicalName"])
    return names


def _checkpoint_owned_devices(obj: Dict) -> Optional[List[str]]:
    claims = _checkpoint_claims(obj)
    return None if claims is None else _owned_devices(claims)


def _scan_journal_file(full: str, base_obj: Optional[Dict]) -> Dict:
    """Offline read of an append-only checkpoint journal: frame/CRC scan
    plus a replay of in-generation records over the base checkpoint, so
    findings (SUBSLICE_ORPHANS, JOURNAL_BLOAT) see the same state the
    plugin would recover — not the stale compacted base."""
    from tpu_dra_driver.plugin import checkpoint as _ckpt

    info: Dict = {}
    try:
        records, good_bytes, bad_index = _ckpt.scan_journal(full)
    except Exception as e:  # noqa: BLE001 — best-effort offline read
        info["error"] = f"{type(e).__name__}: {e}"
        return info
    info["records"] = len(records)
    info["good_bytes"] = good_bytes
    if bad_index is not None:
        info["bad_record_index"] = bad_index
    base_gen = 0
    claims: Dict[str, Dict] = {}
    if base_obj is not None:
        base_gen = int((base_obj.get("journal") or {}).get("gen") or 0)
        claims = dict(_checkpoint_claims(base_obj) or {})
    applied = stale = 0
    for rec in records:
        if rec.gen != base_gen:
            stale += 1
            continue
        applied += 1
        if rec.op == _ckpt.JOURNAL_OP_DEL:
            claims.pop(rec.uid, None)
        elif isinstance(rec.entry, dict):
            claims[rec.uid] = rec.entry
    info["base_gen"] = base_gen
    info["applied"] = applied
    info["stale"] = stale
    info["replayed_owned_devices"] = _owned_devices(claims)
    return info


def collect_state_dir(path: str) -> Dict:
    """Checkpoint files and quarantined corpses under one plugin state
    dir (the ``<checkpoint>.corrupt-<n>`` quarantine convention), plus
    the repartition manager's live-partition manifest
    (``partitions.json``) cross-checked against checkpoint intent — the
    offline half of the SUBSLICE_ORPHANS finding."""
    out: Dict = {"path": path, "checkpoints": [], "quarantined": []}
    if not os.path.isdir(path):
        out["error"] = "not a directory"
        return out
    manifest_partitions: Optional[List[str]] = None
    owned_devices: Optional[List[str]] = None
    base_raw: Optional[Dict] = None
    journal_file: Optional[Tuple[str, str, int]] = None
    for dirpath, _, files in os.walk(path):
        for name in files:
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, path)
            try:
                size = os.path.getsize(full)
            except OSError:
                size = -1
            if ".corrupt-" in name:
                out["quarantined"].append({"file": rel, "bytes": size})
            elif name == "partitions.json":
                raw = _read_json(full)
                if raw is not None:
                    manifest_partitions = [str(p) for p in
                                           raw.get("partitions") or []]
                    out["partitions"] = {
                        "file": rel,
                        "updated_unix": raw.get("updated_unix"),
                        "live": manifest_partitions,
                    }
                out["checkpoints"].append({"file": rel, "bytes": size})
            elif name == "checkpoint.journal":
                journal_file = (full, rel, size)
                out["checkpoints"].append({"file": rel, "bytes": size})
            elif name.endswith((".json", ".chk")) or "checkpoint" in name:
                if name == "checkpoint.json":
                    base_raw = _read_json(full)
                    if base_raw is not None:
                        parsed = _checkpoint_owned_devices(base_raw)
                        if parsed is not None:
                            owned_devices = (owned_devices or []) + parsed
                out["checkpoints"].append({"file": rel, "bytes": size})
    if journal_file is not None:
        full, rel, size = journal_file
        info = _scan_journal_file(full, base_raw)
        info.update({"file": rel, "bytes": size})
        out["journal"] = info
        replayed = info.get("replayed_owned_devices")
        if replayed is not None:
            # journal mode: replayed state supersedes the compacted base
            # (the base alone misses every claim since the last compact)
            owned_devices = list(replayed)
    if manifest_partitions is not None:
        owned = set(owned_devices or [])
        out["subslice_orphans"] = sorted(
            p for p in manifest_partitions if p not in owned)
    return out


def collect_events(clients, limit: int = 200) -> List[Dict]:
    """Recent Events across namespaces, newest last (best-effort)."""
    try:
        events = list(clients.events.list())
    except Exception:  # noqa: BLE001 — API may be the sick part
        return []
    events.sort(key=lambda e: e.get("lastTimestamp") or "")
    return events[-limit:]


def collect(endpoints: Dict[str, str],
            state_dirs: Optional[Dict[str, str]] = None,
            clients=None,
            timeout: float = 3.0,
            resample_after: float = 0.0) -> Dict:
    """The whole bundle: per-component debug surfaces + checkpoint
    state + recent Events."""
    # one shared resample window for the WHOLE fleet: sample everyone,
    # sleep once, resample everyone — collection time stays O(sleep),
    # and every component's delta covers the same wall-clock interval
    components = {name: collect_endpoint(hp, timeout=timeout)
                  for name, hp in endpoints.items()}
    first_state = {name: collect_state_dir(p)
                   for name, p in (state_dirs or {}).items()}
    bundle: Dict = {
        "generated_unix": round(time.time(), 3),
        "components": components,
        "state_dirs": first_state,
    }
    if resample_after > 0:
        # components whose /debug/timeseries ring is armed already hold
        # a real delta window in the first fetch — the sleep-based
        # two-point fallback only covers components WITHOUT the ring
        # (and state dirs, whose byte growth is filesystem-side)
        no_ring = {name: hp for name, hp in endpoints.items()
                   if not _has_timeseries(components[name])}
        if no_ring or state_dirs:
            time.sleep(resample_after)
            for name, hp in no_ring.items():
                resample_metrics(hp, components[name], timeout)
            # state dirs resample too: checkpoint-dir byte growth within
            # the same shared window feeds LEAK_SUSPECTED
            bundle["state_dirs_resample"] = {
                name: collect_state_dir(p)
                for name, p in (state_dirs or {}).items()}
    if clients is not None:
        bundle["events"] = collect_events(clients)
    return bundle


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


def _component_findings(name: str, art: Dict) -> List[Finding]:
    out: List[Finding] = []
    samples = parse_metrics_text(art["metrics"]) if "metrics" in art else {}

    for labels, value in samples.get("dra_circuit_breaker_state", []):
        if value >= 2:
            out.append(Finding(
                CRITICAL, "BREAKER_OPEN", name,
                f"API-server circuit breaker {labels.get('name', '?')!r} "
                f"is OPEN: requests fail fast, kubelet sees NOT_SERVING",
                {"breaker": labels.get("name", "")}))
        elif value >= 1:
            out.append(Finding(
                WARNING, "BREAKER_HALF_OPEN", name,
                f"circuit breaker {labels.get('name', '?')!r} is "
                f"half-open (probing after an outage)"))

    slo_report = art.get("slo") or {}
    for slo_name, row in (slo_report.get("slos") or {}).items():
        if not row.get("burning"):
            continue
        windows = row.get("burning_windows") or []
        wname = windows[0] if windows else "?"
        arms = (row.get("windows") or {}).get(wname, {})
        out.append(Finding(
            CRITICAL, "SLO_BURNING", name,
            f"SLO {slo_name!r} is burning its error budget "
            f"({wname} window, long burn "
            f"{((arms.get('long') or {}).get('burn_rate', 0)):.1f}x, "
            f"budget remaining {row.get('budget_remaining')}): "
            f"{row.get('description', '')}",
            {"slo": slo_name, "windows": windows,
             "budget_remaining": row.get("budget_remaining")}))

    parked = metric_value(samples, "dra_allocator_parked_claims")
    if parked > 0:
        allocator_art = art.get("allocator") or {}
        uids = [c.get("uid", "") for c in
                allocator_art.get("parked_claims") or []]
        reasons = allocator_art.get("parked_reasons") or {}
        why = (f" — by explain-derived reason: "
               f"{dict(sorted(reasons.items()))}" if reasons else "")
        out.append(Finding(
            WARNING, "PARKED_CLAIMS", name,
            f"{int(parked)} ResourceClaim(s) parked as unsatisfiable "
            f"(each carries an AllocationParked Event){why}",
            {"count": int(parked), "uids": uids,
             "by_reason": reasons}))

    residue = (art.get("allocator") or {}).get("residue") or {}
    residue_total = (residue.get("extra_count", 0)
                     + residue.get("missing_count", 0))
    if residue_total > 0:
        out.append(Finding(
            WARNING, "LEDGER_RESIDUE", name,
            f"allocator ledger diverges from the API's live allocations: "
            f"{residue.get('extra_count', 0)} device(s) held by the "
            f"ledger with no live claim (the leak direction), "
            f"{residue.get('missing_count', 0)} allocated in the API but "
            f"unaccounted. A transient entry can be an in-flight commit; "
            f"residue that persists across bundles means releases are "
            f"being missed",
            {"extra_count": residue.get("extra_count", 0),
             "missing_count": residue.get("missing_count", 0),
             "extra": residue.get("extra") or [],
             "missing": residue.get("missing") or [],
             "by_slot": residue.get("by_slot") or {}}))

    owned = [(labels.get("slot", ""), value) for labels, value in
             samples.get("dra_shard_owned_pools", []) if value > 0]
    if len(owned) >= 2:
        counts = [v for _, v in owned]
        mean = sum(counts) / len(counts)
        worst = max(owned, key=lambda kv: kv[1])
        if mean > 0 and worst[1] > 2.0 * mean:
            out.append(Finding(
                WARNING, "SHARD_IMBALANCE", name,
                f"shard slot {worst[0]!r} owns {int(worst[1])} pools vs "
                f"a {mean:.1f} mean across {len(owned)} slots "
                f"(>2x — check ring seed/slot leases)",
                {"slots": dict(owned)}))

    lag_p99 = histogram_quantile(samples, "dra_watch_mux_lag_seconds", 0.99)
    if lag_p99 is not None and lag_p99 > MUX_LAG_P99_THRESHOLD_S:
        out.append(Finding(
            WARNING, "WATCH_MUX_LAG", name,
            f"watch-mux event-to-handler lag p99 >= {lag_p99}s "
            f"(threshold {MUX_LAG_P99_THRESHOLD_S}s): informers are "
            f"falling behind the watch streams",
            {"p99_upper_bound_s": lag_p99}))

    phase_p99 = histogram_quantile_by(
        samples, "dra_allocation_commit_phase_seconds", 0.99, "phase")
    if phase_p99:
        dominant = max(phase_p99, key=phase_p99.get)
        if phase_p99[dominant] >= COMMIT_STALL_P99_THRESHOLD_S:
            out.append(Finding(
                WARNING, "COMMIT_STALL", name,
                f"allocation commit sub-phase {dominant!r} p99 >= "
                f"{phase_p99[dominant]}s (threshold "
                f"{COMMIT_STALL_P99_THRESHOLD_S}s): one phase dominates "
                f"the commit path — cross-reference "
                f"/debug/criticalpath's allocation.commit.* segments "
                f"and the phase's exemplar trace",
                {"phase": dominant,
                 "p99_upper_bound_s": phase_p99[dominant],
                 "per_phase_p99_s": phase_p99}))

    rejections = metric_value(samples, "dra_fencing_rejections_total")
    if rejections > 0:
        by_site = {labels.get("site", "?"): value for labels, value in
                   samples.get("dra_fencing_rejections_total", [])}
        out.append(Finding(
            WARNING, "FENCING_REJECTIONS", name,
            f"{int(rejections)} allocation-plane write(s) were rejected "
            f"by epoch fencing: a paused/partitioned replica acted on a "
            f"lease it no longer held (each rejection PREVENTED a "
            f"split-brain double-allocation; check why the holder "
            f"stalled)",
            {"by_site": by_site}))

    flap_now = metric_value(samples, "dra_leader_transitions_total")
    resample = (parse_metrics_text(art["metrics_resample"])
                if "metrics_resample" in art else None)
    has_ring = _has_timeseries(art)
    flap_delta = (timeseries_delta(art, "dra_leader_transitions_total")
                  if has_ring else None)
    if flap_delta is None and resample is not None:
        flap_delta = metric_value(resample,
                                  "dra_leader_transitions_total") - flap_now
    if flap_delta is not None:
        if flap_delta >= LEASE_FLAP_DELTA_THRESHOLD:
            window = ("the time-series ring's trailing window"
                      if has_ring else "the bundle's resample window")
            out.append(Finding(
                WARNING, "LEASE_FLAPPING", name,
                f"{int(flap_delta)} leadership transition(s) within "
                f"{window}: leases are flapping "
                f"(renewals racing expiry — look for clock trouble, "
                f"API latency, or overloaded holders)",
                {"delta_in_window": int(flap_delta),
                 "source": "timeseries" if has_ring else "resample"}))
    elif flap_now >= LEASE_FLAP_ABSOLUTE_THRESHOLD:
        out.append(Finding(
            WARNING, "LEASE_FLAPPING", name,
            f"{int(flap_now)} lifetime leadership transitions on this "
            f"process: likely lease flapping (collect with --resample "
            f"to confirm it is ongoing)",
            {"total": int(flap_now)}))

    if has_ring:
        # trend fit over the real series: growth over the window AND a
        # positive least-squares slope — a step that already settled
        # (one reconnect wave) no longer pages as a leak
        grew = {}
        for family, threshold in LEAK_GAUGE_DELTAS.items():
            delta = timeseries_delta(art, family)
            slope = timeseries_slope(art, family)
            if delta is not None and delta >= threshold \
                    and slope is not None and slope > 0:
                grew[family] = {"delta_in_window": delta,
                                "slope_per_s": round(slope, 6)}
        if grew:
            out.append(Finding(
                WARNING, "LEAK_SUSPECTED", name,
                f"sustained upward trend over the time-series ring: "
                f"{ {k: v['delta_in_window'] for k, v in grew.items()} } "
                f"with positive least-squares slope — long-horizon decay "
                f"a one-shot scrape cannot see (watchers that are never "
                f"released / parked claims that never drain)",
                {"grew": grew, "source": "timeseries"}))
    elif resample is not None:
        grew = {}
        for family, threshold in LEAK_GAUGE_DELTAS.items():
            delta = metric_value(resample, family) \
                - metric_value(samples, family)
            if delta >= threshold:
                grew[family] = delta
        if grew:
            out.append(Finding(
                WARNING, "LEAK_SUSPECTED", name,
                f"monotone growth within the resample window: "
                f"{ {k: int(v) for k, v in grew.items()} } — long-horizon "
                f"decay a one-shot scrape cannot see (watchers that are "
                f"never released / parked claims that never drain); "
                f"re-collect with a longer --resample to confirm",
                {"grew": grew, "source": "resample"}))

    quarantined = metric_value(samples, "dra_checkpoint_quarantined_total")
    if quarantined > 0:
        out.append(Finding(
            WARNING, "CHECKPOINT_QUARANTINED", name,
            f"{int(quarantined)} corrupt checkpoint(s) quarantined "
            f"(driver restarted from salvaged-or-empty state)"))

    evicted = metric_value(samples, "dra_traces_evicted_total")
    if evicted > 0:
        out.append(Finding(
            INFO, "TRACES_EVICTED", name,
            f"{int(evicted)} trace(s) evicted from the flight recorder: "
            f"/debug/criticalpath attribution covers a partial window"))

    vars_ = art.get("vars") or {}
    if vars_.get("faults_armed"):
        out.append(Finding(
            INFO, "FAULTS_ARMED", name,
            f"fault injection is ARMED: "
            f"{vars_.get('fault_points_armed')} — slow/failed paths may "
            f"be drills, not production faults"))

    for surface, err in (art.get("errors") or {}).items():
        if "404" in err:
            # absent surface (e.g. /debug/allocator on a kubelet
            # plugin) is the normal shape, not a finding
            continue
        out.append(Finding(
            INFO, "SURFACE_UNAVAILABLE", name,
            f"debug surface {surface!r} not collected: {err}"))
    return out


def run_findings(bundle: Dict) -> List[Finding]:
    """Automated triage over a collected bundle, most severe first."""
    findings: List[Finding] = []
    for name, art in (bundle.get("components") or {}).items():
        findings.extend(_component_findings(name, art))
    for name, state in (bundle.get("state_dirs") or {}).items():
        if state.get("quarantined"):
            findings.append(Finding(
                WARNING, "CHECKPOINT_QUARANTINE_FILES", name,
                f"{len(state['quarantined'])} quarantined checkpoint "
                f"file(s) on disk under {state['path']}",
                {"files": [q["file"] for q in state["quarantined"]]}))
        journal = state.get("journal") or {}
        if journal.get("records", 0) > JOURNAL_BLOAT_RECORDS_THRESHOLD:
            findings.append(Finding(
                WARNING, "JOURNAL_BLOAT", name,
                f"checkpoint journal holds {journal['records']} records "
                f"(compaction trigger is "
                f"{JOURNAL_BLOAT_RECORDS_THRESHOLD}) under "
                f"{state['path']}: the compactor is not keeping up — "
                f"replay-on-restart grows with the journal; check "
                f"dra_journal_compaction_seconds and the plugin log for "
                f"swallowed compaction errors",
                {"records": journal.get("records"),
                 "bytes": journal.get("bytes"),
                 "stale": journal.get("stale")}))
        if journal.get("bad_record_index") is not None:
            findings.append(Finding(
                WARNING, "JOURNAL_CORRUPT_RECORDS", name,
                f"checkpoint journal has undecodable record(s) starting "
                f"at index {journal['bad_record_index']} "
                f"({state['path']}): a torn tail is benign (recovery "
                f"truncates it) but mid-file damage quarantines on the "
                f"next restart",
                {"bad_record_index": journal.get("bad_record_index"),
                 "good_bytes": journal.get("good_bytes")}))
        orphans = state.get("subslice_orphans") or []
        if orphans:
            findings.append(Finding(
                WARNING, "SUBSLICE_ORPHANS", name,
                f"{len(orphans)} live sub-slice partition(s) on the node "
                f"with no committed claim in the checkpoint "
                f"({state['path']}): a transient entry can be an "
                f"in-flight prepare; orphans that persist across bundles "
                f"mean the crash-recovery reconcile never ran — restart "
                f"the plugin (its startup sweep tears them down) and "
                f"check dra_subslice_repartitions_total{{op=\"rollback\"}}",
                {"partitions": orphans}))

    def _dir_bytes(state: Dict) -> int:
        return sum(max(0, f.get("bytes", 0))
                   for key in ("checkpoints", "quarantined")
                   for f in state.get(key) or [])

    for name, after in (bundle.get("state_dirs_resample") or {}).items():
        before = (bundle.get("state_dirs") or {}).get(name)
        if before is None or before.get("error") or after.get("error"):
            continue
        growth = _dir_bytes(after) - _dir_bytes(before)
        if growth >= LEAK_STATE_DIR_BYTES_THRESHOLD:
            findings.append(Finding(
                WARNING, "LEAK_SUSPECTED", name,
                f"checkpoint state dir grew {growth} bytes within the "
                f"resample window ({before['path']}): entries are being "
                f"written faster than they are released — a prepare "
                f"path that never unprepares, or quarantine corpses "
                f"accumulating",
                {"bytes_grown": growth, "path": before["path"]}))
    warnings = [e for e in bundle.get("events") or []
                if e.get("type") == "Warning"]
    if warnings:
        by_reason: Dict[str, int] = {}
        for e in warnings:
            by_reason[e.get("reason", "?")] = \
                by_reason.get(e.get("reason", "?"), 0) + 1
        findings.append(Finding(
            INFO, "WARNING_EVENTS", "cluster",
            f"{len(warnings)} Warning Event(s) in the recent window: "
            f"{dict(sorted(by_reason.items()))}"))
    findings.sort(key=lambda f: (_SEVERITY_ORDER.get(f.severity, 9),
                                 f.component, f.code))
    return findings


def summary_text(findings: List[Finding], bundle: Dict) -> str:
    """The triage summary the CLI prints (and the tarball embeds)."""
    lines = [
        "tpu-dra-doctor triage summary",
        f"collected {len(bundle.get('components') or {})} component(s), "
        f"{len(bundle.get('state_dirs') or {})} state dir(s), "
        f"{len(bundle.get('events') or [])} recent event(s)",
        "",
    ]
    if not findings:
        lines.append("no findings: all collected surfaces look healthy")
    for f in findings:
        lines.append(f"[{f.severity.upper():8s}] {f.component}: "
                     f"{f.code}: {f.message}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# bundle tarball
# ---------------------------------------------------------------------------


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float]) -> str:
    """Min-max-normalized unicode sparkline for one series."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK_CHARS[0] * len(values)
    return "".join(
        _SPARK_CHARS[min(len(_SPARK_CHARS) - 1,
                         int((v - lo) / (hi - lo) * len(_SPARK_CHARS)))]
        for v in values)


def component_sparklines(art: Dict, max_series: int = 64,
                         points: int = 60) -> str:
    """One text line per ring series — the at-a-glance shape of a
    component's recent behavior, embedded in the bundle so triage does
    not need a plotting stack."""
    ts = art.get("timeseries") or {}
    series = ts.get("series") or {}
    lines = [f"interval={ts.get('interval_s')}s "
             f"capacity={ts.get('capacity')} series={len(series)}"]
    for key in sorted(series)[:max_series]:
        vals = [v for _, v in series[key][-points:]]
        if not vals:
            continue
        lines.append(f"{key:70s} [{min(vals):.6g}..{max(vals):.6g}] "
                     f"{sparkline(vals)}")
    if len(series) > max_series:
        lines.append(f"... {len(series) - max_series} more series in "
                     f"timeseries.json")
    return "\n".join(lines) + "\n"


def _add_member(tar: tarfile.TarFile, name: str, text: str) -> None:
    data = text.encode()
    info = tarfile.TarInfo(name)
    info.size = len(data)
    info.mtime = int(time.time())
    tar.addfile(info, io.BytesIO(data))


def write_bundle(bundle: Dict, findings: List[Finding],
                 out_path: str) -> str:
    """Write the must-gather tarball: per-component artifacts, events,
    state-dir inventory, machine-readable findings, and the human
    summary. Returns ``out_path``."""
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with tarfile.open(out_path, "w:gz") as tar:
        for name, art in (bundle.get("components") or {}).items():
            for key in ENDPOINT_PATHS:
                if key not in art:
                    continue
                if key == "metrics":
                    _add_member(tar, f"{name}/metrics.txt", art[key])
                else:
                    _add_member(tar, f"{name}/{key}.json",
                                json.dumps(art[key], indent=1))
            if _has_timeseries(art):
                _add_member(tar, f"{name}/sparklines.txt",
                            component_sparklines(art))
            if art.get("errors"):
                _add_member(tar, f"{name}/errors.json",
                            json.dumps(art["errors"], indent=1))
        if bundle.get("events") is not None:
            _add_member(tar, "events.json",
                        json.dumps(bundle["events"], indent=1))
        if bundle.get("state_dirs"):
            _add_member(tar, "state_dirs.json",
                        json.dumps(bundle["state_dirs"], indent=1))
        if bundle.get("state_dirs_resample"):
            _add_member(tar, "state_dirs_resample.json",
                        json.dumps(bundle["state_dirs_resample"], indent=1))
        _add_member(tar, "findings.json",
                    json.dumps([f.to_dict() for f in findings], indent=1))
        _add_member(tar, "summary.txt", summary_text(findings, bundle))
    return out_path
