"""Sub-slice (per-megacore) partition model — the MIG analog.

Reference analog: cmd/gpu-kubelet-plugin/mig.go:33-214 — MIG identity has a
triple representation:

- an *abstract* tuple parseable **from the canonical device name** (how
  crash-recovery re-derives what to tear down without any live handle),
- a *live* tuple describing the concrete created object,
- a *rich* spec carrying the full profile.

We keep exactly that structure for TPU sub-slices. A sub-slice is a
contiguous run of TensorCores on one chip with a proportional HBM share
(megacore generations v4/v5p have 2 cores/chip; a 1-core sub-slice is the
"half chip" unit). Canonical names:

- full chip:  ``tpu-<index>``                              (gpu-<minor>)
- sub-slice:  ``tpu-<index>-ss-<profile>-<start>``         (gpu-…-mig-…)
- profile slot: ``tpu-<index>-prof-<profile>-<slot>``      (DynamicMIG
  profile advertising: a *creatable* shape whose placement the kubelet
  plugin picks at prepare time; ``<slot>`` is an anonymous capacity index,
  NOT a placement start — the concrete placed identity recorded in the
  checkpoint is always a ``-ss-`` name, so crash recovery has one parser)
- shared seat: ``tpu-<index>-mp-<seat>``                   (one multi-process
  client seat on a shared chip — the claim-per-request serving unit)
- passthrough: ``tpu-vfio-<index>``                        (gpu-vfio-<idx>)

where ``<profile>`` is ``<cores>c<hbmGiB>g`` (e.g. ``1c47g`` on v5p) and
``<start>`` is the first core index of the placement. The name regex is the
recovery contract: ``parse_canonical_name`` must round-trip every name this
module can generate (tested in tests/test_partition.py and, for the full
dynamic-picker name space, tests/test_repartition.py).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Union

from tpu_dra_driver.tpulib.topology import GIB, Generation

#: multi-process client seats per shared chip — the claim-per-request
#: serving unit count. Kept equal to api.configs.MAX_MULTI_PROCESS_CLIENTS
#: (pinned by tests/test_repartition.py; defined here so the device
#: library's seat ledger needs no plugin-layer import).
SEAT_COUNT = 16


def seat_core(seat: int, cores: int) -> int:
    """The core a seat's clients run against. Deterministic — the
    repartition placement picker, the ResourceSlice counter model, and
    the device-library seat ledger must all agree on which core a seat
    occupies."""
    return seat * cores // SEAT_COUNT


def seats_per_core(cores: int) -> int:
    return SEAT_COUNT // cores


PROFILE_ID_RE = re.compile(r"^(?P<cores>[0-9]+)c(?P<hbm>[0-9]+)g$")
CHIP_NAME_RE = re.compile(r"^tpu-(?P<index>[0-9]+)$")
SUBSLICE_NAME_RE = re.compile(
    r"^tpu-(?P<index>[0-9]+)-ss-(?P<cores>[0-9]+)c(?P<hbm>[0-9]+)g-(?P<start>[0-9]+)$"
)
VFIO_NAME_RE = re.compile(r"^tpu-vfio-(?P<index>[0-9]+)$")
PROFILE_NAME_RE = re.compile(
    r"^tpu-(?P<index>[0-9]+)-prof-(?P<cores>[0-9]+)c(?P<hbm>[0-9]+)g-(?P<slot>[0-9]+)$"
)
SHARED_NAME_RE = re.compile(r"^tpu-(?P<index>[0-9]+)-mp-(?P<seat>[0-9]+)$")


@dataclass(frozen=True)
class SubsliceProfile:
    """A creatable sub-slice shape on a given generation (MIG profile analog)."""

    generation: Generation
    cores: int

    def __post_init__(self):
        if not (1 <= self.cores <= self.generation.cores_per_chip):
            raise ValueError(
                f"profile {self.cores}c invalid for {self.generation.name} "
                f"({self.generation.cores_per_chip} cores/chip)"
            )

    @property
    def hbm_bytes(self) -> int:
        return self.generation.hbm_bytes_per_core * self.cores

    @property
    def hbm_gib(self) -> int:
        return self.hbm_bytes // GIB

    @property
    def id(self) -> str:
        """Profile string as it appears in canonical names, e.g. ``1c47g``."""
        return f"{self.cores}c{self.hbm_gib}g"

    def placements(self) -> List[int]:
        """Valid placement start core-indices: aligned runs of ``cores``."""
        total = self.generation.cores_per_chip
        return list(range(0, total - self.cores + 1, self.cores))


def profiles_for(generation: Generation) -> List[SubsliceProfile]:
    """All sub-slice profiles a generation supports.

    Power-of-two core counts that divide the chip (for 2-core megacore
    chips: 1c and 2c; single-core chips support no strict sub-slice, only
    the full chip).
    """
    out = []
    c = 1
    while c <= generation.cores_per_chip:
        if generation.cores_per_chip % c == 0:
            out.append(SubsliceProfile(generation, c))
        c *= 2
    return out


@dataclass(frozen=True)
class SubsliceSpecTuple:
    """Abstract identity — fully recoverable from the canonical name.

    Reference analog: MigSpecTuple (mig.go:33-56): parent minor + GI profile
    id + placement start.
    """

    parent_index: int     # chip index (accel minor)
    profile_id: str       # e.g. "1c47g"
    placement_start: int  # first core index

    def canonical_name(self) -> str:
        return f"tpu-{self.parent_index}-ss-{self.profile_id}-{self.placement_start}"


@dataclass(frozen=True)
class SubsliceSpec:
    """Rich spec used to actually create a sub-slice."""

    parent_index: int
    parent_uuid: str
    profile: SubsliceProfile
    placement_start: int

    def __post_init__(self):
        if self.placement_start not in self.profile.placements():
            raise ValueError(
                f"placement start {self.placement_start} invalid for profile "
                f"{self.profile.id} on {self.profile.generation.name}"
            )

    @property
    def tuple(self) -> SubsliceSpecTuple:
        return SubsliceSpecTuple(self.parent_index, self.profile.id, self.placement_start)

    def canonical_name(self) -> str:
        return self.tuple.canonical_name()


@dataclass(frozen=True)
class SubsliceLiveTuple:
    """Concrete identity of a created sub-slice (MigLiveTuple analog:
    GIID/CIID/UUID → partition id + devfs path + uuid)."""

    uuid: str             # stable id of the live partition
    partition_id: int     # kernel/runtime partition handle
    devfs_path: str       # device node the container gets


ParsedName = Union["ParsedChip", "ParsedSubslice", "ParsedVfio",
                   "ParsedProfile", "ParsedShared"]


@dataclass(frozen=True)
class ParsedChip:
    index: int


@dataclass(frozen=True)
class ParsedSubslice:
    tuple: SubsliceSpecTuple


@dataclass(frozen=True)
class ParsedVfio:
    index: int


@dataclass(frozen=True)
class ParsedProfile:
    """An advertised *creatable* profile slot. Carries no placement — the
    concrete placed sub-slice a claim ends up with is recorded in the
    checkpoint under its ``-ss-`` canonical name, so this parse result
    only ever appears for allocation-result names, never for recovery."""

    parent_index: int
    profile_id: str       # e.g. "1c47g"
    slot: int             # anonymous capacity index, not a core start


@dataclass(frozen=True)
class ParsedShared:
    """A multi-process client seat on a shared chip."""

    parent_index: int
    seat: int


def canonical_chip_name(index: int) -> str:
    return f"tpu-{index}"


def canonical_vfio_name(index: int) -> str:
    return f"tpu-vfio-{index}"


def canonical_profile_name(parent_index: int, profile: SubsliceProfile,
                           slot: int) -> str:
    return f"tpu-{parent_index}-prof-{profile.id}-{slot}"


def canonical_shared_name(parent_index: int, seat: int) -> str:
    return f"tpu-{parent_index}-mp-{seat}"


def canonical_subslice_name(parent_index: int, profile: SubsliceProfile,
                            placement_start: int) -> str:
    return SubsliceSpecTuple(parent_index, profile.id, placement_start).canonical_name()


def parse_profile_id(profile_id: str) -> tuple[int, int]:
    """Parse a ``<cores>c<hbmGiB>g`` profile id → (cores, hbm_gib).

    The single owner of the profile-id format (fake/native backends must not
    re-derive it by ad-hoc string splitting). Raises ValueError on mismatch.
    """
    m = PROFILE_ID_RE.match(profile_id)
    if not m:
        raise ValueError(f"unparseable sub-slice profile id {profile_id!r}")
    return int(m.group("cores")), int(m.group("hbm"))


def parse_canonical_name(name: str) -> Optional[ParsedName]:
    """Parse any canonical device name back to its abstract identity.

    This is the crash-recovery entry point (reference mig.go:184-214 parses
    MIG canonical names with a regex for the same reason): after a plugin
    restart, checkpointed device names alone must be enough to identify
    which live partitions to tear down.
    """
    m = CHIP_NAME_RE.match(name)
    if m:
        return ParsedChip(int(m.group("index")))
    m = SUBSLICE_NAME_RE.match(name)
    if m:
        profile_id = f"{int(m.group('cores'))}c{int(m.group('hbm'))}g"
        return ParsedSubslice(
            SubsliceSpecTuple(int(m.group("index")), profile_id, int(m.group("start")))
        )
    m = VFIO_NAME_RE.match(name)
    if m:
        return ParsedVfio(int(m.group("index")))
    m = PROFILE_NAME_RE.match(name)
    if m:
        profile_id = f"{int(m.group('cores'))}c{int(m.group('hbm'))}g"
        return ParsedProfile(int(m.group("index")), profile_id,
                             int(m.group("slot")))
    m = SHARED_NAME_RE.match(name)
    if m:
        return ParsedShared(int(m.group("index")), int(m.group("seat")))
    return None
