"""TPU generation and slice topology model.

Reference analog: go-nvlib's device/MIG-profile model plus the NVML
fabric/clique info (cmd/compute-domain-kubelet-plugin/nvlib.go:188-356).
For TPUs the topology is not free-form NVLink cliques but a fixed ICI
torus: a slice of shape (x, y, z) chips, partitioned across hosts in
whole-host granules. The "clique id" analog is the slice identifier plus
the deterministic host→coordinate assignment.

Nominal per-generation constants (cores, HBM, ICI) are the public
datasheet-level numbers; they feed ResourceSlice attributes/capacities and
the bench's bandwidth targets, not any runtime decision.
"""

from __future__ import annotations

import itertools
import re
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

GIB = 1024 ** 3


@dataclass(frozen=True)
class Generation:
    """Static description of one TPU generation."""

    name: str                 # "v4", "v5e", "v5p", "v6e"
    product_name: str
    cores_per_chip: int       # 2 for megacore generations (v4/v5p), else 1
    hbm_bytes: int            # per chip
    chips_per_host: int       # default host granule
    torus_dims: int           # 3 for v4/v5p, 2 for v5e/v6e
    ici_links_per_chip: int
    ici_link_gbps: int        # per-direction per-link, nominal
    sparsecores_per_chip: int = 0

    @property
    def ici_bandwidth_gbps(self) -> int:
        return self.ici_links_per_chip * self.ici_link_gbps

    @property
    def hbm_bytes_per_core(self) -> int:
        return self.hbm_bytes // self.cores_per_chip


GENERATIONS: Dict[str, Generation] = {
    g.name: g
    for g in (
        Generation("v4", "TPU v4", 2, 32 * GIB, 4, 3, 6, 400, 0),
        Generation("v5e", "TPU v5e", 1, 16 * GIB, 4, 2, 4, 400, 0),
        Generation("v5p", "TPU v5p", 2, 95 * GIB, 4, 3, 6, 800, 4),
        Generation("v6e", "TPU v6e (Trillium)", 1, 32 * GIB, 4, 2, 4, 896, 2),
    )
}

_SLICE_NAME_RE = re.compile(r"^(?P<gen>v[0-9]+[ep]?)-(?P<cores>[0-9]+)$")

# GCE metadata / gcloud spellings -> canonical generation names. The
# metadata server reports v5e slices as "v5litepod-N" (and v5p existed
# briefly as "v5pod-N"); the driver speaks the canonical short form.
_GEN_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "v5pod": "v5p",
}


def normalize_accelerator_type(accel_type: str) -> str:
    """Map GCE spellings onto the canonical ``v<gen>-<cores>`` grammar."""
    accel_type = accel_type.strip()
    gen, sep, cores = accel_type.partition("-")
    if sep and gen in _GEN_ALIASES:
        return f"{_GEN_ALIASES[gen]}-{cores}"
    return accel_type


@dataclass(frozen=True)
class SliceTopology:
    """A concrete slice: e.g. ``v5p-16`` = 8 chips = 2 hosts, torus (2,2,2).

    The accelerator-type naming convention counts *TensorCores*, so
    ``v5p-16`` is 16 cores / 8 chips / 2 hosts. Host→coordinate assignment
    is deterministic: hosts own contiguous x-major blocks of the torus, so
    a given ``(slice, host_index)`` always maps to the same chip coords —
    this is the TPU analog of the NVLink clique-id derivation (the fabric
    reachability group is a property of physical wiring, not free choice).
    """

    generation: Generation
    shape: Tuple[int, ...]          # chips per torus dimension

    @classmethod
    def from_accelerator_type(cls, accel_type: str) -> "SliceTopology":
        accel_type = normalize_accelerator_type(accel_type)
        m = _SLICE_NAME_RE.match(accel_type)
        if not m:
            raise ValueError(f"unparseable accelerator type {accel_type!r}")
        gen = GENERATIONS.get(m.group("gen"))
        if gen is None:
            raise ValueError(f"unknown TPU generation in {accel_type!r}")
        cores = int(m.group("cores"))
        if cores <= 0 or cores % gen.cores_per_chip:
            raise ValueError(f"{accel_type!r}: core count not divisible by "
                             f"{gen.cores_per_chip}-core chips")
        chips = cores // gen.cores_per_chip
        return cls(gen, _default_shape(chips, gen.torus_dims))

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def num_cores(self) -> int:
        return self.num_chips * self.generation.cores_per_chip

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.generation.chips_per_host)

    @property
    def accelerator_type(self) -> str:
        return f"{self.generation.name}-{self.num_cores}"

    @property
    def topology_string(self) -> str:
        """libtpu-style ``TPU_TOPOLOGY`` value, e.g. ``2x2x2``."""
        return "x".join(str(d) for d in self.shape)

    def chip_coords(self) -> list[Tuple[int, ...]]:
        """All chip coordinates in deterministic x-major order."""
        return [tuple(reversed(c))
                for c in itertools.product(*(range(d) for d in reversed(self.shape)))]

    def coords_for_host(self, host_index: int) -> list[Tuple[int, ...]]:
        """The chip coordinates owned by host ``host_index``.

        Hosts own contiguous blocks in x-major order; with the default
        4-chip host granule on a torus whose x-dim is a multiple of the
        granule this matches the physical tray wiring.
        """
        n = self.num_hosts
        if not (0 <= host_index < n):
            raise ValueError(f"host_index {host_index} out of range [0,{n})")
        per_host = self.num_chips // n
        coords = self.chip_coords()
        return coords[host_index * per_host:(host_index + 1) * per_host]

    def chips_per_host_grid(self) -> Tuple[int, ...]:
        """Per-host chip sub-grid, e.g. (2, 2, 1) for 4-chip v5p hosts."""
        grid = []
        remaining = self.generation.chips_per_host
        for d in self.shape:
            g = _gcd_block(d, remaining)
            grid.append(g)
            remaining = max(1, remaining // g)
        return tuple(grid)

    def bounds_for_host(self, host_index: int) -> str:
        """libtpu ``TPU_HOST_BOUNDS``-style string describing the host grid
        (hosts per torus dimension) — the same for every host, but validated
        against this host's index."""
        if not (0 <= host_index < self.num_hosts):
            raise ValueError(f"host_index {host_index} out of range [0,{self.num_hosts})")
        grid = self.chips_per_host_grid()
        return ",".join(str(d // g) for d, g in zip(self.shape, grid))

    def worker_env(self, host_index: int, hostnames: Iterable[str]) -> Dict[str, str]:
        """The bootstrap env a worker on ``host_index`` needs for libtpu to
        bring up ICI across the slice — the TPU analog of the IMEX
        nodes-config file (reference cmd/compute-domain-daemon renders the
        IMEX config; here env vars are the whole contract)."""
        names = list(hostnames)
        return {
            "TPU_WORKER_ID": str(host_index),
            "TPU_WORKER_HOSTNAMES": ",".join(names),
            "TPU_ACCELERATOR_TYPE": self.accelerator_type,
            "TPU_TOPOLOGY": self.topology_string,
            "TPU_HOST_BOUNDS": self.bounds_for_host(host_index),
            "TPU_CHIPS_PER_HOST_BOUNDS": _chips_per_host_bounds(self),
            "TPU_RUNTIME_METRICS_PORTS": "8431",
        }


def _default_shape(chips: int, dims: int) -> Tuple[int, ...]:
    """Standard torus shapes: factor the chip count into `dims` near-equal
    powers-of-two-ish factors, largest last (x-major convention: shape is
    (x, y, z) with x fastest)."""
    if dims == 2:
        x = _largest_factor_le_sqrt(chips)
        return (x, chips // x)
    # dims == 3
    best: Optional[Tuple[int, int, int]] = None
    for x in range(1, chips + 1):
        if chips % x:
            continue
        rest = chips // x
        for y in range(x, rest + 1):
            if rest % y:
                continue
            z = rest // y
            if z < y:
                continue
            cand = (x, y, z)
            if best is None or _spread(cand) < _spread(best):
                best = cand
    assert best is not None
    return best


def _spread(t: Tuple[int, ...]) -> int:
    return max(t) - min(t)


def _largest_factor_le_sqrt(n: int) -> int:
    best = 1
    f = 1
    while f * f <= n:
        if n % f == 0:
            best = f
        f += 1
    return best


def _gcd_block(dim: int, granule: int) -> int:
    g = min(dim, granule)
    while g > 1 and dim % g:
        g -= 1
    return max(g, 1)


def _chips_per_host_bounds(topo: SliceTopology) -> str:
    """Chips-per-host sub-grid string, e.g. ``2,2,1`` for 4-chip v5p hosts."""
    return ",".join(str(c) for c in topo.chips_per_host_grid())
