"""The TpuLib interface — every hardware touchpoint behind one seam.

Reference analog: the set of operations gpu-kubelet-plugin performs against
NVML/go-nvlib/nvidia-smi (cmd/gpu-kubelet-plugin/nvlib.go): enumeration,
MIG create/destroy, health events, compute-mode/time-slice knobs, vfio
driver flips. The reference calls these through concrete cgo types, which
is why it is untestable without hardware (SURVEY.md §4). Here the seam is
explicit: :class:`TpuLib` with a native and a fake implementation.
"""

from __future__ import annotations

import abc
import threading
from dataclasses import dataclass
from enum import Enum
from typing import Callable, Dict, List, Optional, Tuple

from tpu_dra_driver.tpulib.partition import (
    SubsliceLiveTuple,
    SubsliceSpec,
    SubsliceSpecTuple,
)
from tpu_dra_driver.tpulib.topology import Generation, SliceTopology


class TpuLibError(RuntimeError):
    pass


class SubsliceAlreadyExistsError(TpuLibError):
    pass


class SubsliceNotFoundError(TpuLibError):
    pass


class SharingExhaustedError(TpuLibError):
    """A multi-process share cannot be granted: over-subscribed limits or
    the chip already carries another owner's share. Permanent — retrying
    without a config/claim change cannot succeed (the reference surfaces
    the analogous MPS daemon failures as non-retryable,
    sharing.go:151-436)."""


@dataclass(frozen=True)
class MultiProcessShare:
    """A granted per-claim multi-process share on one chip: up to
    ``max_clients`` processes, each bounded to ``client_hbm_bytes`` of
    HBM. The driver-level ledger entry backing the env the CDI spec
    injects — the runtime (libtpu) enforces the budgets at allocation
    time; the fake backend models that enforcement so tests can prove
    two clients really get disjoint bounded shares (the reference's MPS
    control daemon materially enforces the same way,
    sharing.go:151-436)."""

    chip_uuid: str
    owner: str                 # claim uid holding the share
    max_clients: int
    hbm_limit_percent: int
    client_hbm_bytes: int
    #: seat index for the claim-per-request SEAT model (SharedChipServing:
    #: one share per claim, many claims per chip); -1 for the legacy
    #: whole-chip single-owner share.
    seat: int = -1


@dataclass(frozen=True)
class ChipInfo:
    """Everything enumeration learns about one chip.

    Reference analog: GpuInfo from nvlib.go:428-566 (uuid, minor, memory,
    architecture, brand, pciBusID, addressing mode, MIG capability).
    """

    index: int                    # accel device minor ("/dev/accel<index>")
    uuid: str                     # stable chip id
    generation: Generation
    pci_address: str              # e.g. "0000:00:05.0"
    pci_root: str                 # PCIe root complex (topology-alignment attr)
    serial: str
    devfs_path: str               # "/dev/accel<index>" (or vfio group path)
    vfio_group: Optional[str]     # set when bound to vfio-pci
    coords: Tuple[int, ...]       # ICI torus coordinates within the slice
    host_index: int
    slice_id: str                 # clique-id analog: slice identifier
    driver_version: str
    firmware_version: str

    @property
    def product_name(self) -> str:
        return self.generation.product_name

    @property
    def hbm_bytes(self) -> int:
        return self.generation.hbm_bytes

    @property
    def cores(self) -> int:
        return self.generation.cores_per_chip


class HealthEventKind(Enum):
    # TPU analog of NVML XID critical / ECC events (device_health.go:30-121)
    DEVICE_ERROR = "DeviceError"          # chip-fatal runtime error
    HBM_ECC_ERROR = "HbmEccError"         # uncorrectable HBM error
    ICI_LINK_ERROR = "IciLinkError"       # fabric link down/flap
    THERMAL = "ThermalSlowdown"
    PREEMPTED = "Preempted"               # maintenance event


@dataclass(frozen=True)
class HealthEvent:
    kind: HealthEventKind
    chip_uuid: str
    code: int = 0
    message: str = ""


class TimesliceInterval(Enum):
    """Time-slice scheduling interval for multi-process chip sharing.

    Reference analog: api sharing.go:167-180 (Default/Short/Medium/Long →
    nvidia-smi compute-policy --set-timeslice).
    """

    DEFAULT = "Default"
    SHORT = "Short"
    MEDIUM = "Medium"
    LONG = "Long"

    def micros(self) -> int:
        return {"Default": 0, "Short": 1000, "Medium": 2000, "Long": 5000}[self.value]


@dataclass
class LiveSubslice:
    spec_tuple: SubsliceSpecTuple
    live: SubsliceLiveTuple


class TpuLib(abc.ABC):
    """Abstract native boundary. All methods are thread-safe."""

    # -- enumeration --------------------------------------------------------

    @abc.abstractmethod
    def enumerate_chips(self) -> List[ChipInfo]:
        """All chips visible on this host, passthrough-bound ones included
        (their ``vfio_group`` is set)."""

    @abc.abstractmethod
    def host_topology(self) -> SliceTopology:
        """The slice this host belongs to."""

    @abc.abstractmethod
    def host_index(self) -> int:
        """This host's index within the slice (worker-id source of truth)."""

    @abc.abstractmethod
    def slice_id(self) -> str:
        """Stable identifier of the ICI slice (clique-id analog)."""

    # -- sub-slice partitioning (MIG analog) --------------------------------

    @abc.abstractmethod
    def create_subslice(self, spec: SubsliceSpec) -> SubsliceLiveTuple:
        """Create a live sub-slice. Raises SubsliceAlreadyExistsError if the
        placement is occupied."""

    @abc.abstractmethod
    def destroy_subslice(self, tup: SubsliceSpecTuple) -> None:
        """Destroy by abstract identity (crash recovery path: identity comes
        from a parsed canonical name, no live handle needed)."""

    @abc.abstractmethod
    def list_subslices(self) -> List[LiveSubslice]:
        """All live sub-slices on this host (source for
        DestroyUnknownSubslices at startup)."""

    # -- sharing knobs ------------------------------------------------------

    @abc.abstractmethod
    def set_timeslice(self, chip_uuid: str, interval: TimesliceInterval) -> None: ...

    @abc.abstractmethod
    def set_exclusive_mode(self, chip_uuid: str, exclusive: bool) -> None: ...

    @abc.abstractmethod
    def allocate_multiprocess_share(self, chip_uuid: str, owner: str,
                                    max_clients: int,
                                    hbm_limit_percent: int) -> MultiProcessShare:
        """Grant a per-claim multi-process share. Raises
        SharingExhaustedError when max_clients * hbm_limit_percent > 100
        (the clients' combined ceilings cannot exceed the chip) or the
        chip already carries a different owner's share. Idempotent for
        the same owner (re-prepare returns the existing grant)."""

    @abc.abstractmethod
    def release_multiprocess_share(self, chip_uuid: str,
                                   owner: Optional[str] = None) -> None:
        """Release the chip's share (any owner when ``owner`` is None —
        the unprepare path tears down whatever the claim left). No-op
        when none exists."""

    @abc.abstractmethod
    def get_multiprocess_share(self, chip_uuid: str) -> Optional[MultiProcessShare]: ...

    # -- multi-owner client seats (claim-per-request serving) ---------------

    @abc.abstractmethod
    def attach_multiprocess_seat(self, chip_uuid: str, owner: str,
                                 seat: int,
                                 hbm_limit_percent: int) -> MultiProcessShare:
        """Grant ONE client seat on a shared chip to ``owner`` (a claim
        uid). Unlike :meth:`allocate_multiprocess_share` (one owner whose
        own processes share the chip), seats admit many owners per chip —
        the claim-per-request serving model. Raises SharingExhaustedError
        (permanent) when the seat is held by another owner, the chip
        carries a legacy whole-chip share, or the aggregate HBM percent
        would exceed the chip; raises plain TpuLibError (transient —
        retriable after re-placement) when the seat's core hosts a live
        sub-slice partition. Idempotent for the same (owner, seat)."""

    @abc.abstractmethod
    def detach_multiprocess_seat(self, chip_uuid: str,
                                 owner: Optional[str] = None,
                                 seat: Optional[int] = None) -> None:
        """Release seats matching ``owner`` and/or ``seat`` (both None =
        every seat — the unprepare-sweep shape). No-op when none match;
        connected clients of a released seat are disconnected."""

    @abc.abstractmethod
    def list_multiprocess_seats(self, chip_uuid: str
                                ) -> Dict[int, MultiProcessShare]:
        """Live seats on the chip, by seat index."""

    # -- health -------------------------------------------------------------

    @abc.abstractmethod
    def subscribe_health(self, callback: Callable[[HealthEvent], None]) -> Callable[[], None]:
        """Register a health-event callback; returns an unsubscribe fn."""

    # -- passthrough (vfio) -------------------------------------------------

    @abc.abstractmethod
    def current_driver(self, pci_address: str) -> Optional[str]: ...

    @abc.abstractmethod
    def bind_to_vfio(self, pci_address: str) -> str:
        """Unbind from the TPU runtime driver, bind to vfio-pci; returns the
        vfio group path."""

    @abc.abstractmethod
    def unbind_from_vfio(self, pci_address: str) -> None: ...

    @abc.abstractmethod
    def device_in_use(self, pci_address: str) -> bool:
        """True if any process holds the device node (fuser analog)."""

    # -- versions -----------------------------------------------------------

    @abc.abstractmethod
    def driver_version(self) -> str: ...


class HealthHub:
    """Shared fan-out helper for health subscriptions."""

    def __init__(self):
        self._mu = threading.Lock()
        self._subs: Dict[int, Callable[[HealthEvent], None]] = {}
        self._next = 0

    def subscribe(self, cb: Callable[[HealthEvent], None]) -> Callable[[], None]:
        with self._mu:
            token = self._next
            self._next += 1
            self._subs[token] = cb

        def unsub():
            with self._mu:
                self._subs.pop(token, None)

        return unsub

    def publish(self, event: HealthEvent) -> None:
        with self._mu:
            subs = list(self._subs.values())
        for cb in subs:
            cb(event)
