"""GCE metadata-server client: hardware-derived slice/worker identity.

Reference analog: the clique-identity probe in
cmd/compute-domain-kubelet-plugin/nvlib.go:188-356, which asks the
*hardware* (NVML fabric info) rather than trusting deployment env. On a
real TPU VM the authoritative identity source is the GCE metadata server
(169.254.169.254 / metadata.google.internal): the TPU control plane
publishes the accelerator type, this VM's worker number, and the
slice-wide worker endpoints as instance attributes, plus a ``tpu-env``
attribute carrying the libtpu bootstrap env block.

Resolution order used by :class:`NativeTpuLib`: explicit config >
metadata server > ``TPU_*`` env vars > derived defaults — so operators
can still hand-feed identity (air-gapped bring-up, tests), but a stock
GKE/GCE deployment needs nothing.

Override knobs (also the test seam): ``GCE_METADATA_HOST`` (the
convention Google client libraries honor) points the client at a fake
server; no env var and no reachable server -> ``available()`` is False
and everything degrades to the env/default path.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

DEFAULT_HOST = "169.254.169.254"
_ATTR_BASE = "/computeMetadata/v1/instance/attributes/"


@dataclass
class TpuMetadata:
    """What the metadata server knows about this worker's slice."""

    accelerator_type: str = ""          # e.g. "v5p-16"
    worker_id: Optional[int] = None     # this host's index in the slice
    worker_endpoints: List[str] = field(default_factory=list)  # peer IPs
    slice_id: str = ""                  # from tpu-env (MEGASCALE/SLICE id)
    tpu_env: Dict[str, str] = field(default_factory=dict)


def parse_tpu_env(blob: str) -> Dict[str, str]:
    """The ``tpu-env`` attribute is a newline-separated KEY: 'value'
    block (YAML-ish, values may be quoted)."""
    out: Dict[str, str] = {}
    for line in blob.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or ":" not in line:
            continue
        k, _, v = line.partition(":")
        v = v.strip().strip("'\"")
        out[k.strip()] = v
    return out


class MetadataClient:
    """Minimal metadata-server client (requests-based, no SDK)."""

    def __init__(self, host: Optional[str] = None, timeout: float = 0.5,
                 probe_attempts: int = 3):
        self._host = (host or os.environ.get("GCE_METADATA_HOST")
                      or DEFAULT_HOST)
        if "://" not in self._host:
            self._host = f"http://{self._host}"
        self._timeout = timeout
        self._probe_attempts = max(1, probe_attempts)
        self._mu = threading.Lock()
        self._available: Optional[bool] = None

    def available(self) -> bool:
        """Cached reachability probe (the canonical flavor check). The
        metadata server can be briefly unreachable during VM boot
        (Google client libraries retry for exactly this reason), so the
        first determination retries before caching a negative — a wrong
        "unavailable" here silently degrades identity to env/inference."""
        with self._mu:
            if self._available is not None:
                return self._available
        import time

        import requests
        ok = False
        for attempt in range(self._probe_attempts):
            try:
                resp = requests.get(f"{self._host}/computeMetadata/v1/",
                                    headers={"Metadata-Flavor": "Google"},
                                    timeout=self._timeout)
                ok = (resp.status_code == 200
                      and resp.headers.get("Metadata-Flavor") == "Google")
                if ok:
                    break
            except requests.RequestException:
                ok = False
            if attempt + 1 < self._probe_attempts:
                time.sleep(0.3)
        with self._mu:
            self._available = ok
        return ok

    def instance_attribute(self, name: str) -> Optional[str]:
        if not self.available():
            return None
        import requests
        try:
            resp = requests.get(f"{self._host}{_ATTR_BASE}{name}",
                                headers={"Metadata-Flavor": "Google"},
                                timeout=self._timeout)
            if resp.status_code == 200:
                return resp.text
        except requests.RequestException as e:
            log.warning("metadata attribute %s: %s", name, e)
        return None

    def tpu_metadata(self) -> Optional[TpuMetadata]:
        """None when no metadata server is reachable or the VM carries no
        TPU attributes (a CPU node in the same pool)."""
        if not self.available():
            return None
        accel = self.instance_attribute("accelerator-type") or ""
        worker = self.instance_attribute("agent-worker-number")
        endpoints_raw = self.instance_attribute("worker-network-endpoints") or ""
        tpu_env = parse_tpu_env(self.instance_attribute("tpu-env") or "")
        if not accel and not tpu_env:
            return None
        # worker-network-endpoints entries are ":"-separated records
        # whose last field is the worker IP. Validate the extracted
        # token as an actual IP literal instead of trusting field
        # position: an IPv6 address carries colons INSIDE the field, so
        # rsplit alone would yield only its last hextet (ADVICE r3).
        # Records carry two prefix fields (worker name, uuid) before the
        # IP, so the IP is everything from field 3 on — parsed by FIELD
        # POSITION first, which handles IPv6 exactly (colons inside the
        # address stay attached). Only if that remainder fails to parse
        # do we fall back to the longest valid-IP suffix (tolerates
        # extra prefix fields); longest-first, because "db8::1" is
        # itself valid IPv6 and a shorter match would silently truncate
        # — and conversely a hex-like prefix field could be absorbed,
        # which is why position is primary, not the scan. Entries with
        # no parseable IP are skipped with a warning: a wrong peer IP
        # is worse than a missing one.
        import ipaddress

        def _valid_ip(s):
            try:
                ipaddress.ip_address(s)
                return True
            except ValueError:
                return False

        endpoints = []
        for rec in endpoints_raw.split(","):
            rec = rec.strip()
            if not rec:
                continue
            parts = rec.split(":")
            ip = None
            positional = ":".join(parts[2:]).strip() if len(parts) > 2 else ""
            if positional and _valid_ip(positional):
                ip = positional
            else:
                for take in range(len(parts), 0, -1):
                    candidate = ":".join(parts[-take:]).strip()
                    if _valid_ip(candidate):
                        ip = candidate
                        break
            if ip is None:
                log.warning("worker-network-endpoints: no parseable IP "
                            "in record %r; skipping", rec)
                continue
            endpoints.append(ip)
        worker_id: Optional[int] = None
        if worker is not None and worker.strip().isdigit():
            worker_id = int(worker.strip())
        elif tpu_env.get("WORKER_ID", "").isdigit():
            worker_id = int(tpu_env["WORKER_ID"])
        slice_id = (tpu_env.get("MEGASCALE_SLICE_ID")
                    or tpu_env.get("TPU_SLICE_ID")
                    or tpu_env.get("SLICE_ID", ""))
        if not accel:
            accel = tpu_env.get("ACCELERATOR_TYPE", "")
        # GCE reports v5e as "v5litepod-N" etc.; canonicalize here so every
        # consumer sees the driver's grammar
        from tpu_dra_driver.tpulib.topology import normalize_accelerator_type
        accel = normalize_accelerator_type(accel) if accel else accel
        return TpuMetadata(accelerator_type=accel, worker_id=worker_id,
                           worker_endpoints=endpoints, slice_id=slice_id,
                           tpu_env=tpu_env)
