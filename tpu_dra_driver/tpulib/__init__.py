"""tpulib — the native device boundary for TPUs.

Reference analog: the cgo/NVML boundary (github.com/NVIDIA/go-nvml +
go-nvlib) used by cmd/gpu-kubelet-plugin/nvlib.go. Here the substrate is:

- ``/dev/accel*`` + ``/sys/class/accel`` device nodes (TPU runtime driver),
- ``/dev/vfio/<group>`` for passthrough-bound chips,
- PCI discovery via ``/sys/bus/pci/devices`` (Google vendor id 0x1ae0),
- libtpu-style topology metadata (generation, chips/host, ICI torus coords).

Three implementations of :class:`tpu_dra_driver.tpulib.interface.TpuLib`:

- :mod:`tpu_dra_driver.tpulib.fake`   — faithful in-memory fake (the test
  seam the reference lacks; SURVEY.md §4/§7).
- :mod:`tpu_dra_driver.tpulib.native` — ctypes binding to the C++
  ``libtpudev.so`` (native/tpudevlib) which does the real sysfs/devfs walk
  and owns the live sub-slice partition registry.
- a sysfs-walking pure-Python fallback inside ``native.py`` when the shared
  library is unavailable.
"""

from tpu_dra_driver.tpulib.interface import (  # noqa: F401
    TpuLib,
    TpuLibError,
    ChipInfo,
    HealthEvent,
)
from tpu_dra_driver.tpulib.topology import (  # noqa: F401
    Generation,
    GENERATIONS,
    SliceTopology,
)
from tpu_dra_driver.tpulib.partition import (  # noqa: F401
    SubsliceProfile,
    SubsliceSpec,
    SubsliceSpecTuple,
    SubsliceLiveTuple,
    canonical_chip_name,
    canonical_subslice_name,
    parse_canonical_name,
)
