"""NativeTpuLib — ctypes binding to the C++ ``libtpudev.so`` boundary.

Reference analog: the cgo binding in go-nvml. The C++ library
(native/tpudevlib) does the real work: sysfs PCI walk (vendor 0x1ae0),
flock'd partition registry, vfio driver_override flips, /proc fd scans.
This wrapper adapts it to the :class:`tpu_dra_driver.tpulib.interface.TpuLib`
seam and fills in what sysfs cannot know:

- **slice topology / host identity** come from the deployment environment
  (``TPU_ACCELERATOR_TYPE``, ``TPU_WORKER_ID``, metadata server in
  production) — sysfs only sees this host's PCI functions;
- **scheduling knobs** (time-slice interval, exclusive mode) are runtime
  configuration on TPU, not ioctls: they're recorded in the state dir and
  take effect through the CDI env the driver injects (the nvidia-smi
  compute-policy analog);
- **health events** come from the native poller in ``libtpudev``
  (``tpudev_health_poll``: PCIe AER fatal/nonfatal counters, TPU driver
  error counters on the PCI device dir, surprise-removal detection — the
  NVML-event-set analog, device_health.go:30-351). A JSONL spool file
  remains as the secondary *injection* path for tests and external
  monitoring agents.
"""

from __future__ import annotations

import ctypes
import json
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from tpu_dra_driver.tpulib.interface import (
    ChipInfo,
    HealthEvent,
    HealthEventKind,
    HealthHub,
    LiveSubslice,
    MultiProcessShare,
    SharingExhaustedError,
    SubsliceAlreadyExistsError,
    SubsliceNotFoundError,
    TimesliceInterval,
    TpuLib,
    TpuLibError,
)
from tpu_dra_driver.tpulib.partition import (
    SubsliceLiveTuple,
    SubsliceSpec,
    SubsliceSpecTuple,
)
from tpu_dra_driver.tpulib.topology import GENERATIONS, SliceTopology

_GEN_BY_CODE = {4: "v4", 50: "v5e", 51: "v5p", 60: "v6e"}


class NativeUnavailableError(TpuLibError):
    pass


class _ChipStruct(ctypes.Structure):
    _fields_ = [
        ("index", ctypes.c_int32),
        ("pci_address", ctypes.c_char * 32),
        ("pci_root", ctypes.c_char * 32),
        ("devfs_path", ctypes.c_char * 96),
        ("vfio_group", ctypes.c_char * 96),
        ("driver", ctypes.c_char * 32),
        ("generation", ctypes.c_int32),
        ("cores", ctypes.c_int32),
        ("hbm_bytes", ctypes.c_int64),
        ("serial", ctypes.c_char * 64),
        ("uuid", ctypes.c_char * 96),
    ]


class _PartStruct(ctypes.Structure):
    _fields_ = [
        ("parent_index", ctypes.c_int32),
        ("cores", ctypes.c_int32),
        ("placement_start", ctypes.c_int32),
        ("partition_id", ctypes.c_int64),
        ("uuid", ctypes.c_char * 96),
        ("devfs_path", ctypes.c_char * 96),
    ]


class _HealthEventStruct(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_int32),
        ("code", ctypes.c_int32),
        ("chip_uuid", ctypes.c_char * 96),
        ("message", ctypes.c_char * 160),
    ]


_HEALTH_KIND_BY_CODE = {
    1: HealthEventKind.DEVICE_ERROR,
    2: HealthEventKind.HBM_ECC_ERROR,
    3: HealthEventKind.ICI_LINK_ERROR,
    4: HealthEventKind.THERMAL,
}


def _default_library_paths() -> List[str]:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return [
        os.environ.get("TPUDEV_LIBRARY", ""),
        os.path.join(here, "native", "libtpudev.so"),
        "/usr/local/lib/libtpudev.so",
        "libtpudev.so",
    ]


def load_library(path: Optional[str] = None) -> ctypes.CDLL:
    candidates = [path] if path else _default_library_paths()
    last: Optional[Exception] = None
    for cand in candidates:
        if not cand:
            continue
        try:
            lib = ctypes.CDLL(cand)
            lib.tpudev_version.restype = ctypes.c_char_p
            return lib
        except OSError as e:
            last = e
    raise NativeUnavailableError(
        f"libtpudev.so not found (tried {candidates}); build it with "
        f"`make -C native`: {last}")


@dataclass
class NativeSystemConfig:
    sysfs_root: str = "/sys"
    devfs_root: str = "/dev"
    proc_root: str = "/proc"
    state_dir: str = "/var/lib/tpu-dra-driver/native"
    accelerator_type: Optional[str] = None   # default: $TPU_ACCELERATOR_TYPE
    host_index: Optional[int] = None         # default: $TPU_WORKER_ID or 0
    slice_id: Optional[str] = None           # default: $TPU_SLICE_ID or derived
    health_spool: Optional[str] = None       # default: <state_dir>/health-events.jsonl
    library_path: Optional[str] = None
    # GCE metadata server: the authoritative identity source on real TPU
    # VMs (tpulib/metadata.py). None -> GCE_METADATA_HOST env or the
    # well-known 169.254.169.254; use_metadata=False skips the probe.
    metadata_host: Optional[str] = None
    use_metadata: bool = True
    # verify vfio flips actually took effect against the kernel; test
    # harnesses with inert (no-kernel) sysfs trees disable this
    strict_vfio_verify: bool = True


class NativeTpuLib(TpuLib):
    MAX_CHIPS = 64
    MAX_PARTS = 256

    def __init__(self, config: NativeSystemConfig | None = None):
        self._cfg = config or NativeSystemConfig()
        self._lib = load_library(self._cfg.library_path)
        os.makedirs(self._cfg.state_dir, exist_ok=True)
        self._mu = threading.RLock()
        self._health = HealthHub()
        self._health_thread: Optional[threading.Thread] = None
        self._health_stop = threading.Event()
        self._health_offset = 0
        self._sched_path = os.path.join(self._cfg.state_dir, "sched.json")
        self._indices_path = os.path.join(self._cfg.state_dir, "indices.json")
        self._driver_version = self._lib.tpudev_version().decode()
        self._chips_cache: Optional[List[ChipInfo]] = None

        # Identity resolution: explicit config > GCE metadata server >
        # TPU_* env > inference/defaults (reference analog: clique id from
        # the hardware probe, nvlib.go:188-356 — env is the fallback, not
        # the source of truth).
        md = None
        if self._cfg.use_metadata and not (
                self._cfg.accelerator_type is not None
                and self._cfg.host_index is not None
                and self._cfg.slice_id is not None):
            from tpu_dra_driver.tpulib.metadata import MetadataClient
            import logging
            md = MetadataClient(host=self._cfg.metadata_host).tpu_metadata()
            if md is not None:
                logging.getLogger(__name__).info(
                    "identity from GCE metadata: accel=%s worker=%s slice=%s",
                    md.accelerator_type, md.worker_id, md.slice_id)
            elif not os.environ.get("TPU_ACCELERATOR_TYPE"):
                # No metadata AND no env: identity will be inferred from
                # local chips (single-host assumption). Wrong on a
                # multi-host slice whose metadata server was unreachable
                # at boot — shout about it.
                logging.getLogger(__name__).warning(
                    "no GCE metadata server and no TPU_* env: inferring "
                    "single-host identity from local chips; on a "
                    "multi-host slice this publishes WRONG topology")

        accel = (self._cfg.accelerator_type
                 or (md.accelerator_type if md else None)
                 or os.environ.get("TPU_ACCELERATOR_TYPE"))
        if not accel:
            # single-host default: infer from the number of local chips
            raw = self._enumerate_raw()
            if not raw:
                raise TpuLibError(
                    "no TPU chips found and no TPU_ACCELERATOR_TYPE set")
            gen_code = raw[0].generation
            gen = GENERATIONS[_GEN_BY_CODE.get(gen_code, "v5p")]
            accel = f"{gen.name}-{len(raw) * gen.cores_per_chip}"
        self._topo = SliceTopology.from_accelerator_type(accel)
        hi = self._cfg.host_index
        if hi is None and md is not None:
            hi = md.worker_id
        if hi is None:
            hi = int(os.environ.get("TPU_WORKER_ID", "0"))
        self._host_index = hi
        self._slice_id = (self._cfg.slice_id
                          or (md.slice_id if md else None)
                          or os.environ.get("TPU_SLICE_ID")
                          or f"slice-{accel}")

    # ------------------------------------------------------------------

    def _err(self) -> ctypes.Array:
        return ctypes.create_string_buffer(512)

    def _enumerate_raw(self) -> List[_ChipStruct]:
        out = (_ChipStruct * self.MAX_CHIPS)()
        err = self._err()
        n = self._lib.tpudev_enumerate(
            self._cfg.sysfs_root.encode(), self._cfg.devfs_root.encode(),
            out, self.MAX_CHIPS, err, len(err))
        if n < 0:
            raise TpuLibError(f"enumerate: {err.value.decode()}")
        return list(out[:n])

    def _stable_index(self, pci_address: str, raw_index: int,
                      index_map: Dict[str, int]) -> int:
        """Device identity (``tpu-<index>``) must survive vfio flips, which
        remove the accel minor. The first observation of each PCI address
        persists its index; later enumerations reuse it regardless of what
        the kernel currently exposes."""
        if pci_address in index_map:
            return index_map[pci_address]
        idx = raw_index
        if idx < 0 or idx in index_map.values():
            used = set(index_map.values())
            idx = 0
            while idx in used:
                idx += 1
        index_map[pci_address] = idx
        return idx

    def _load_indices(self) -> Dict[str, int]:
        try:
            with open(self._indices_path) as f:
                return {k: int(v) for k, v in json.load(f).items()}
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _store_indices(self, index_map: Dict[str, int]) -> None:
        tmp = f"{self._indices_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(index_map, f, indent=1, sort_keys=True)
        os.replace(tmp, self._indices_path)

    def enumerate_chips(self, refresh: bool = False) -> List[ChipInfo]:
        with self._mu:
            if self._chips_cache is not None and not refresh:
                return list(self._chips_cache)
            raw = self._enumerate_raw()
            coords = self._topo.coords_for_host(self._host_index)
            index_map = self._load_indices()
            chips = []
            for c in raw:
                gen = GENERATIONS[_GEN_BY_CODE.get(c.generation, "v5p")]
                vfio = c.vfio_group.decode() or None
                idx = self._stable_index(c.pci_address.decode(), c.index,
                                         index_map)
                devfs = c.devfs_path.decode()
                if not devfs and not vfio:
                    devfs = f"{self._cfg.devfs_root}/accel{idx}"
                chips.append(ChipInfo(
                    index=idx,
                    uuid=c.uuid.decode(),
                    generation=gen,
                    pci_address=c.pci_address.decode(),
                    pci_root=c.pci_root.decode(),
                    serial=c.serial.decode(),
                    devfs_path=devfs,
                    vfio_group=vfio,
                    # coords keyed by the STABLE index, not array position
                    coords=coords[idx] if idx < len(coords) else (idx,),
                    host_index=self._host_index,
                    slice_id=self._slice_id,
                    driver_version=self._driver_version,
                    firmware_version="",
                ))
            self._store_indices(index_map)
            chips.sort(key=lambda c: c.index)
            self._chips_cache = chips
            return list(chips)

    def host_topology(self) -> SliceTopology:
        return self._topo

    def host_index(self) -> int:
        return self._host_index

    def slice_id(self) -> str:
        return self._slice_id

    # ------------------------------------------------------------------

    def create_subslice(self, spec: SubsliceSpec) -> SubsliceLiveTuple:
        with self._mu:
            chip = self._chip_by_index(spec.parent_index)
            if chip.uuid != spec.parent_uuid:
                raise TpuLibError(
                    f"uuid mismatch for chip {spec.parent_index}")
            out = _PartStruct()
            err = self._err()
            rc = self._lib.tpudev_partition_create(
                self._cfg.state_dir.encode(), self._cfg.devfs_root.encode(),
                spec.parent_index, spec.profile.cores, spec.placement_start,
                chip.cores, ctypes.byref(out), err, len(err))
            if rc == -2:
                raise SubsliceAlreadyExistsError(err.value.decode())
            if rc != 0:
                raise TpuLibError(f"create_subslice: {err.value.decode()}")
            return SubsliceLiveTuple(
                uuid=out.uuid.decode(),
                partition_id=out.partition_id,
                devfs_path=out.devfs_path.decode())

    def destroy_subslice(self, tup: SubsliceSpecTuple) -> None:
        from tpu_dra_driver.tpulib.partition import parse_profile_id
        cores, _ = parse_profile_id(tup.profile_id)
        err = self._err()
        rc = self._lib.tpudev_partition_destroy(
            self._cfg.state_dir.encode(), tup.parent_index, cores,
            tup.placement_start, err, len(err))
        if rc == -3:
            raise SubsliceNotFoundError(err.value.decode())
        if rc != 0:
            raise TpuLibError(f"destroy_subslice: {err.value.decode()}")

    def list_subslices(self) -> List[LiveSubslice]:
        out = (_PartStruct * self.MAX_PARTS)()
        err = self._err()
        n = self._lib.tpudev_partition_list(
            self._cfg.state_dir.encode(), out, self.MAX_PARTS, err, len(err))
        if n < 0:
            raise TpuLibError(f"list_subslices: {err.value.decode()}")
        result = []
        chips = {c.index: c for c in self.enumerate_chips()}
        for p in out[:n]:
            chip = chips.get(p.parent_index)
            gen = chip.generation if chip else GENERATIONS["v5p"]
            hbm_gib = (gen.hbm_bytes_per_core * p.cores) >> 30
            tup = SubsliceSpecTuple(p.parent_index,
                                    f"{p.cores}c{hbm_gib}g",
                                    p.placement_start)
            result.append(LiveSubslice(
                spec_tuple=tup,
                live=SubsliceLiveTuple(uuid=p.uuid.decode(),
                                       partition_id=p.partition_id,
                                       devfs_path=p.devfs_path.decode())))
        return sorted(result, key=lambda l: l.spec_tuple.canonical_name())

    # ------------------------------------------------------------------
    # scheduling knobs (recorded state; applied via CDI env at prepare)
    # ------------------------------------------------------------------

    def _load_sched(self) -> Dict:
        try:
            with open(self._sched_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _store_sched(self, sched: Dict) -> None:
        tmp = f"{self._sched_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(sched, f, indent=1, sort_keys=True)
        os.replace(tmp, self._sched_path)

    def set_timeslice(self, chip_uuid: str, interval: TimesliceInterval) -> None:
        with self._mu:
            self._assert_chip(chip_uuid)
            sched = self._load_sched()
            sched.setdefault(chip_uuid, {})["timeslice"] = interval.value
            self._store_sched(sched)

    def set_exclusive_mode(self, chip_uuid: str, exclusive: bool) -> None:
        with self._mu:
            self._assert_chip(chip_uuid)
            sched = self._load_sched()
            sched.setdefault(chip_uuid, {})["exclusive"] = exclusive
            self._store_sched(sched)

    def get_timeslice(self, chip_uuid: str) -> TimesliceInterval:
        v = self._load_sched().get(chip_uuid, {}).get("timeslice", "Default")
        return TimesliceInterval(v)

    def get_exclusive_mode(self, chip_uuid: str) -> bool:
        return bool(self._load_sched().get(chip_uuid, {}).get("exclusive", False))

    # -- multi-process share ledger (persisted like the scheduler knobs:
    # a crashed plugin's grants survive and unprepare can release them;
    # runtime budget enforcement itself is libtpu's job — the driver's
    # ledger prevents double-grants and over-subscribed configs, the
    # reference's MPS-daemon-bookkeeping analog, sharing.go:151-436) ----

    def allocate_multiprocess_share(self, chip_uuid: str, owner: str,
                                    max_clients: int,
                                    hbm_limit_percent: int) -> MultiProcessShare:
        with self._mu:
            chip = self._assert_chip(chip_uuid)
            sched = self._load_sched()
            entry = sched.get(chip_uuid, {}).get("mp_share")
            if entry is not None:
                if entry.get("owner") == owner:
                    return MultiProcessShare(
                        chip_uuid=chip_uuid, owner=owner,
                        max_clients=entry["max_clients"],
                        hbm_limit_percent=entry["hbm_limit_percent"],
                        client_hbm_bytes=entry["client_hbm_bytes"])
                raise SharingExhaustedError(
                    f"chip {chip_uuid} already shared by claim "
                    f"{entry.get('owner')}")
            if max_clients * hbm_limit_percent > 100:
                raise SharingExhaustedError(
                    f"over-subscribed: {max_clients} clients x "
                    f"{hbm_limit_percent}% HBM exceeds the chip")
            share = MultiProcessShare(
                chip_uuid=chip_uuid, owner=owner, max_clients=max_clients,
                hbm_limit_percent=hbm_limit_percent,
                client_hbm_bytes=chip.hbm_bytes * hbm_limit_percent // 100)
            sched.setdefault(chip_uuid, {})["mp_share"] = {
                "owner": owner, "max_clients": max_clients,
                "hbm_limit_percent": hbm_limit_percent,
                "client_hbm_bytes": share.client_hbm_bytes,
            }
            self._store_sched(sched)
            return share

    def release_multiprocess_share(self, chip_uuid: str,
                                   owner: Optional[str] = None) -> None:
        with self._mu:
            sched = self._load_sched()
            entry = sched.get(chip_uuid, {}).get("mp_share")
            if entry is None:
                return
            if owner is not None and entry.get("owner") != owner:
                raise TpuLibError(
                    f"share on {chip_uuid} owned by {entry.get('owner')}, "
                    f"not {owner}")
            del sched[chip_uuid]["mp_share"]
            self._store_sched(sched)

    def get_multiprocess_share(self, chip_uuid: str) -> Optional[MultiProcessShare]:
        entry = self._load_sched().get(chip_uuid, {}).get("mp_share")
        if entry is None:
            return None
        return MultiProcessShare(
            chip_uuid=chip_uuid, owner=entry.get("owner", ""),
            max_clients=entry["max_clients"],
            hbm_limit_percent=entry["hbm_limit_percent"],
            client_hbm_bytes=entry["client_hbm_bytes"])

    # -- multi-owner client seats (persisted like the whole-chip share:
    # a crashed plugin's seats survive and unprepare detaches them) --------

    @staticmethod
    def _seat_share(chip_uuid: str, seat: int, entry: Dict
                    ) -> MultiProcessShare:
        return MultiProcessShare(
            chip_uuid=chip_uuid, owner=entry.get("owner", ""),
            max_clients=1,
            hbm_limit_percent=entry["hbm_limit_percent"],
            client_hbm_bytes=entry["client_hbm_bytes"], seat=seat)

    def attach_multiprocess_seat(self, chip_uuid: str, owner: str,
                                 seat: int,
                                 hbm_limit_percent: int) -> MultiProcessShare:
        from tpu_dra_driver.tpulib.partition import SEAT_COUNT
        with self._mu:
            chip = self._assert_chip(chip_uuid)
            if not (0 <= seat < SEAT_COUNT):
                raise TpuLibError(f"seat {seat} outside [0, {SEAT_COUNT})")
            sched = self._load_sched()
            if sched.get(chip_uuid, {}).get("mp_share") is not None:
                raise SharingExhaustedError(
                    f"chip {chip_uuid} carries a whole-chip share; seats "
                    f"cannot coexist with it")
            seats = sched.setdefault(chip_uuid, {}).setdefault(
                "mp_seats", {})
            existing = seats.get(str(seat))
            if existing is not None:
                if existing.get("owner") == owner:
                    return self._seat_share(chip_uuid, seat, existing)
                raise SharingExhaustedError(
                    f"seat {seat} on chip {chip_uuid} held by claim "
                    f"{existing.get('owner')}")
            total_pct = sum(e["hbm_limit_percent"] for e in seats.values())
            if total_pct + hbm_limit_percent > 100:
                raise SharingExhaustedError(
                    f"chip {chip_uuid}: aggregate seat HBM "
                    f"{total_pct + hbm_limit_percent}% exceeds the chip")
            entry = {"owner": owner,
                     "hbm_limit_percent": hbm_limit_percent,
                     "client_hbm_bytes":
                         chip.hbm_bytes * hbm_limit_percent // 100}
            seats[str(seat)] = entry
            self._store_sched(sched)
            return self._seat_share(chip_uuid, seat, entry)

    def detach_multiprocess_seat(self, chip_uuid: str,
                                 owner: Optional[str] = None,
                                 seat: Optional[int] = None) -> None:
        with self._mu:
            sched = self._load_sched()
            seats = sched.get(chip_uuid, {}).get("mp_seats")
            if not seats:
                return
            victims = [k for k, e in seats.items()
                       if (owner is None or e.get("owner") == owner)
                       and (seat is None or int(k) == seat)]
            for k in victims:
                del seats[k]
            if not seats:
                sched[chip_uuid].pop("mp_seats", None)
            self._store_sched(sched)

    def list_multiprocess_seats(self, chip_uuid: str
                                ) -> Dict[int, MultiProcessShare]:
        seats = self._load_sched().get(chip_uuid, {}).get("mp_seats") or {}
        return {int(k): self._seat_share(chip_uuid, int(k), e)
                for k, e in seats.items()}

    def _assert_chip(self, chip_uuid: str) -> ChipInfo:
        for c in self.enumerate_chips():
            if c.uuid == chip_uuid:
                return c
        raise TpuLibError(f"no chip with uuid {chip_uuid}")

    def _chip_by_index(self, index: int) -> ChipInfo:
        for c in self.enumerate_chips():
            if c.index == index:
                return c
        raise TpuLibError(f"no chip with index {index}")

    # ------------------------------------------------------------------
    # health: native sysfs poller (primary) + JSONL spool (injection)
    # ------------------------------------------------------------------

    @property
    def health_spool_path(self) -> str:
        return (self._cfg.health_spool
                or os.path.join(self._cfg.state_dir, "health-events.jsonl"))

    def subscribe_health(self, callback: Callable[[HealthEvent], None]) -> Callable[[], None]:
        unsub = self._health.subscribe(callback)
        with self._mu:
            if self._health_thread is None:
                self._health_stop.clear()
                self._health_thread = threading.Thread(
                    target=self._poll_health, daemon=True, name="tpudev-health")
                self._health_thread.start()
        return unsub

    def _native_health_poller(self):
        """Create the C-side poller; None when the loaded .so predates the
        health API (binding stays compatible with older builds)."""
        if not hasattr(self._lib, "tpudev_health_poll"):
            return None
        self._lib.tpudev_health_poller_new.restype = ctypes.c_void_p
        return self._lib.tpudev_health_poller_new(
            self._cfg.sysfs_root.encode(), self._cfg.devfs_root.encode())

    def _poll_native_health(self, poller,
                            max_out: int = 64) -> List[HealthEvent]:
        """One native poll. A full buffer (len == max_out) may mean
        truncation; the C side keeps the affected chips' baselines so
        dropped deltas re-emit on the next poll — poll again rather
        than assuming quiet."""
        out = (_HealthEventStruct * max_out)()
        err = self._err()
        n = self._lib.tpudev_health_poll(ctypes.c_void_p(poller), out,
                                         max_out, err, len(err))
        if n < 0:
            raise TpuLibError(f"health poll: {err.value.decode()}")
        return [HealthEvent(
                    kind=_HEALTH_KIND_BY_CODE.get(
                        e.kind, HealthEventKind.DEVICE_ERROR),
                    chip_uuid=e.chip_uuid.decode(),
                    code=e.code,
                    message=e.message.decode())
                for e in out[:n]]

    # The native poll re-enumerates the PCI bus and reads per-chip counter
    # files; the counters are cumulative so nothing is lost by polling
    # slowly. The spool tail is cheap (one open+seek) and is the
    # low-latency injection seam, so it keeps the tight cadence.
    NATIVE_HEALTH_POLL_INTERVAL = 5.0
    SPOOL_POLL_INTERVAL = 0.2

    def _poll_health(self) -> None:
        import logging
        import time as _time
        log = logging.getLogger(__name__)
        poller = self._native_health_poller()
        next_native = 0.0   # first pass primes the native baseline
        while not self._health_stop.wait(self.SPOOL_POLL_INTERVAL):
            # The poller must survive anything — a dead health thread means
            # degraded-device handling silently stops for the process
            # lifetime.
            # Primary source: the native sysfs poller (AER + TPU driver
            # counters + surprise removal), the NVML event-set analog.
            if poller is not None and _time.monotonic() >= next_native:
                next_native = _time.monotonic() + self.NATIVE_HEALTH_POLL_INTERVAL
                try:
                    for event in self._poll_native_health(poller):
                        try:
                            self._health.publish(event)
                        except Exception:
                            log.exception("health subscriber failed for %s",
                                          event)
                except Exception:
                    log.exception("native health poll failed")
            # Secondary: the JSONL spool — the injection seam for tests
            # and for external monitoring agents that see signals sysfs
            # cannot (libtpu runtime errors, maintenance notices). Binary
            # mode so offsets are byte-exact even with multibyte messages
            # or partially-written lines.
            try:
                with open(self.health_spool_path, "rb") as f:
                    f.seek(self._health_offset)
                    for raw_line in f:
                        if not raw_line.endswith(b"\n"):
                            break  # partial write; re-read next poll
                        self._health_offset += len(raw_line)
                        line = raw_line.strip()
                        if not line:
                            continue
                        try:
                            d = json.loads(line)
                            event = HealthEvent(
                                kind=HealthEventKind(d["kind"]),
                                chip_uuid=d.get("chip_uuid", ""),
                                code=d.get("code", 0),
                                message=d.get("message", ""))
                        except (ValueError, KeyError):
                            continue
                        try:
                            self._health.publish(event)
                        except Exception:
                            log.exception("health subscriber failed for %s",
                                          event)
            except FileNotFoundError:
                pass
            except Exception:
                log.exception("health spool poll failed")
        if poller is not None:
            self._lib.tpudev_health_poller_free(ctypes.c_void_p(poller))

    def close(self) -> None:
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=1.0)
            self._health_thread = None

    # ------------------------------------------------------------------
    # vfio
    # ------------------------------------------------------------------

    def current_driver(self, pci_address: str) -> Optional[str]:
        out = ctypes.create_string_buffer(64)
        self._lib.tpudev_current_driver(
            self._cfg.sysfs_root.encode(), pci_address.encode(), out, len(out))
        return out.value.decode() or None

    def bind_to_vfio(self, pci_address: str) -> str:
        group = ctypes.create_string_buffer(128)
        err = self._err()
        rc = self._lib.tpudev_vfio_bind(
            self._cfg.sysfs_root.encode(), pci_address.encode(),
            1 if self._cfg.strict_vfio_verify else 0,
            group, len(group), err, len(err))
        if rc != 0:
            raise TpuLibError(f"vfio bind {pci_address}: {err.value.decode()}")
        with self._mu:
            self._chips_cache = None  # devfs/vfio personality changed
        return group.value.decode()

    def unbind_from_vfio(self, pci_address: str) -> None:
        err = self._err()
        rc = self._lib.tpudev_vfio_unbind(
            self._cfg.sysfs_root.encode(), pci_address.encode(), err, len(err))
        if rc != 0:
            raise TpuLibError(f"vfio unbind {pci_address}: {err.value.decode()}")
        with self._mu:
            self._chips_cache = None

    def device_in_use(self, pci_address: str) -> bool:
        chip = None
        for c in self.enumerate_chips():
            if c.pci_address == pci_address:
                chip = c
                break
        if chip is None:
            return False
        return bool(self._lib.tpudev_device_in_use(
            self._cfg.proc_root.encode(), chip.devfs_path.encode()))

    # ------------------------------------------------------------------

    def driver_version(self) -> str:
        return self._lib.tpudev_version().decode()
