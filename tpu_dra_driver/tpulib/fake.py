"""In-memory fake TpuLib — the hardware-free test seam.

The reference has **no** fake/mock hardware backend (SURVEY.md §4); every
meaningful test needs a real GPU cluster. This fake closes that gap: the
entire plugin stack (enumeration → ResourceSlices → Prepare/Unprepare →
CDI → crash recovery) runs against it in unit tests and in the in-repo e2e
harness.

Fidelity points deliberately modeled on real behavior:

- deterministic chip UUIDs/PCI addresses derived from (slice_id, host,
  index), so restarts "re-enumerate" identical hardware;
- live sub-slices survive a *plugin* restart but not a *host* restart
  (mirrors MIG): state lives in a shared registry object (or an optional
  state file) that outlives the plugin object in tests;
- occupancy conflicts: overlapping placements and double-creates fail like
  NVML does;
- optional fault injection: fail-next-op, health-event publishing, op
  latency to exercise timeout paths.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from tpu_dra_driver.tpulib.interface import (
    ChipInfo,
    HealthEvent,
    HealthHub,
    LiveSubslice,
    MultiProcessShare,
    SharingExhaustedError,
    SubsliceAlreadyExistsError,
    SubsliceNotFoundError,
    TimesliceInterval,
    TpuLib,
    TpuLibError,
)
from tpu_dra_driver.pkg import faultinject as fi
from tpu_dra_driver.tpulib.partition import (
    SEAT_COUNT,
    SubsliceLiveTuple,
    SubsliceSpec,
    SubsliceSpecTuple,
    parse_profile_id,
    seat_core,
)
from tpu_dra_driver.tpulib.topology import SliceTopology

# Device-library fault points (enumeration flaps, partition-op failures):
# every FakeTpuLib op funnels through _op, which also fires the global
# "tpulib.<op>" point — so the chaos drill matrix scripts hardware
# misbehavior the same way it scripts REST/checkpoint faults, on top of
# the per-instance fail_next/set_op_latency seams below.
for _op_name in ("enumerate_chips", "create_subslice", "destroy_subslice",
                 "set_timeslice", "set_exclusive_mode",
                 "allocate_multiprocess_share", "release_multiprocess_share",
                 "attach_multiprocess_seat", "detach_multiprocess_seat",
                 "bind_to_vfio", "unbind_from_vfio"):
    fi.register(f"tpulib.{_op_name}",
                f"FakeTpuLib {_op_name} (fail=TpuLibError-style flap, "
                f"latency=slow device runtime)")
fi.register("tpulib.health_event",
            "one published health event (corrupt mutates the event; "
            "drills flood this to model health-event storms)")


def _stable_hex(*parts: object, n: int = 8) -> str:
    h = hashlib.sha256("/".join(str(p) for p in parts).encode()).hexdigest()
    return h[:n]


@dataclass
class FakeSystemConfig:
    """Describes the fake host: which slice it sits in and where."""

    accelerator_type: str = "v5p-16"   # 16 cores = 8 chips = 2 hosts
    host_index: int = 0
    slice_id: Optional[str] = None     # default: derived from accel type
    driver_version: str = "fake-tpu-driver 1.0"
    firmware_version: str = "fake-fw 2026.07"
    devfs_root: str = "/dev"           # prefix for fabricated device paths

    def resolved_slice_id(self) -> str:
        return self.slice_id or f"slice-{_stable_hex(self.accelerator_type, 'default')}"


@dataclass
class _HostState:
    """Hardware-side state that outlives a plugin process (like real MIG
    partitions / vfio bindings do). Share one _HostState between FakeTpuLib
    instances to simulate plugin restarts."""

    subslices: Dict[SubsliceSpecTuple, SubsliceLiveTuple] = field(default_factory=dict)
    vfio_bound: Dict[str, str] = field(default_factory=dict)   # pci -> group path
    timeslice: Dict[str, TimesliceInterval] = field(default_factory=dict)
    exclusive: Dict[str, bool] = field(default_factory=dict)
    in_use: Set[str] = field(default_factory=set)              # pci addresses
    next_partition_id: int = 1
    next_vfio_group: int = 10
    # multi-process sharing ledger: chip uuid -> grant, plus the modeled
    # runtime contention state (connected clients and their allocations)
    mp_shares: Dict[str, MultiProcessShare] = field(default_factory=dict)
    mp_clients: Dict[str, Dict[int, int]] = field(default_factory=dict)
    mp_next_client: int = 1
    # multi-owner client seats (claim-per-request serving): chip uuid ->
    # seat index -> per-claim share; client cid -> owning seat's owner
    mp_seats: Dict[str, Dict[int, MultiProcessShare]] = field(default_factory=dict)
    mp_client_owner: Dict[str, Dict[int, str]] = field(default_factory=dict)


class FakeTpuLib(TpuLib):
    def __init__(self, config: FakeSystemConfig | None = None,
                 host_state: _HostState | None = None):
        self._cfg = config or FakeSystemConfig()
        self._topo = SliceTopology.from_accelerator_type(self._cfg.accelerator_type)
        if not (0 <= self._cfg.host_index < self._topo.num_hosts):
            raise TpuLibError(
                f"host_index {self._cfg.host_index} out of range for "
                f"{self._cfg.accelerator_type} ({self._topo.num_hosts} hosts)"
            )
        self._state = host_state if host_state is not None else _HostState()
        self._mu = threading.RLock()
        self._health = HealthHub()
        self._fail_next: Dict[str, TpuLibError] = {}
        self._op_latency = 0.0
        self._chips = self._build_chips()

    # -- fake-only controls -------------------------------------------------

    @property
    def host_state(self) -> _HostState:
        """Expose hardware-side state so tests can hand it to a 'restarted'
        plugin's fresh FakeTpuLib."""
        return self._state

    def fail_next(self, op: str, error: TpuLibError | None = None) -> None:
        self._fail_next[op] = error or TpuLibError(f"injected failure in {op}")

    def set_op_latency(self, seconds: float) -> None:
        self._op_latency = seconds

    def inject_health_event(self, event: HealthEvent) -> None:
        event = fi.fire("tpulib.health_event", payload=event)
        self._health.publish(event)

    def inject_health_flood(self, events: List[HealthEvent]) -> None:
        """Publish a burst back-to-back — the health-event-storm drill
        (subscribers must coalesce, not amplify, a flood)."""
        for ev in events:
            self.inject_health_event(ev)

    def _op(self, name: str) -> None:
        fi.fire(f"tpulib.{name}")
        if self._op_latency:
            time.sleep(self._op_latency)
        err = self._fail_next.pop(name, None)
        if err is not None:
            raise err

    # -- enumeration --------------------------------------------------------

    def _build_chips(self) -> List[ChipInfo]:
        gen = self._topo.generation
        slice_id = self._cfg.resolved_slice_id()
        coords = self._topo.coords_for_host(self._cfg.host_index)
        chips = []
        for i, xyz in enumerate(coords):
            uuid = f"TPU-{_stable_hex(slice_id, self._cfg.host_index, i, n=32)}"
            bus = 4 + i
            chips.append(
                ChipInfo(
                    index=i,
                    uuid=uuid,
                    generation=gen,
                    pci_address=f"0000:{bus:02x}:00.0",
                    pci_root=f"pci0000:{bus:02x}",
                    serial=f"FAKE{_stable_hex(uuid, n=10).upper()}",
                    devfs_path=os.path.join(self._cfg.devfs_root, f"accel{i}"),
                    vfio_group=None,
                    coords=xyz,
                    host_index=self._cfg.host_index,
                    slice_id=slice_id,
                    driver_version=self._cfg.driver_version,
                    firmware_version=self._cfg.firmware_version,
                )
            )
        return chips

    def enumerate_chips(self) -> List[ChipInfo]:
        with self._mu:
            self._op("enumerate_chips")
            out = []
            for c in self._chips:
                group = self._state.vfio_bound.get(c.pci_address)
                if group is not None:
                    c = dataclasses.replace(c, vfio_group=group, devfs_path=group)
                out.append(c)
            return out

    def host_topology(self) -> SliceTopology:
        return self._topo

    def host_index(self) -> int:
        return self._cfg.host_index

    def slice_id(self) -> str:
        return self._cfg.resolved_slice_id()

    # -- sub-slices ---------------------------------------------------------

    def _chip_by_index(self, index: int) -> ChipInfo:
        for c in self._chips:
            if c.index == index:
                return c
        raise TpuLibError(f"no chip with index {index}")

    def create_subslice(self, spec: SubsliceSpec) -> SubsliceLiveTuple:
        with self._mu:
            self._op("create_subslice")
            chip = self._chip_by_index(spec.parent_index)
            if chip.uuid != spec.parent_uuid:
                raise TpuLibError(
                    f"uuid mismatch for chip {spec.parent_index}: "
                    f"{spec.parent_uuid} != {chip.uuid}"
                )
            tup = spec.tuple
            if tup in self._state.subslices:
                raise SubsliceAlreadyExistsError(f"sub-slice {tup.canonical_name()} exists")
            # occupancy check: any live sub-slice overlapping the core range
            lo = spec.placement_start
            hi = lo + spec.profile.cores
            # a core hosting multi-process client seats cannot also be
            # partitioned (the per-core exclusion the counter model and
            # the repartition placement picker both honor)
            for seat, share in self._state.mp_seats.get(chip.uuid,
                                                        {}).items():
                core = seat_core(seat, chip.cores)
                if lo <= core < hi:
                    raise TpuLibError(
                        f"core {core} of chip {spec.parent_index} carries "
                        f"multi-process seat {seat} (owner {share.owner})")
            for other in self._state.subslices:
                if other.parent_index != spec.parent_index:
                    continue
                try:
                    ocores, _ = parse_profile_id(other.profile_id)
                except ValueError as e:
                    raise TpuLibError(str(e)) from e
                olo = other.placement_start
                ohi = olo + ocores
                if lo < ohi and olo < hi:
                    raise SubsliceAlreadyExistsError(
                        f"placement [{lo},{hi}) overlaps live sub-slice "
                        f"{other.canonical_name()}"
                    )
            pid = self._state.next_partition_id
            self._state.next_partition_id += 1
            live = SubsliceLiveTuple(
                uuid=f"TPUSS-{_stable_hex(chip.uuid, tup.profile_id, tup.placement_start, n=24)}",
                partition_id=pid,
                devfs_path=f"{chip.devfs_path}_pt{lo}",
            )
            self._state.subslices[tup] = live
            return live

    def destroy_subslice(self, tup: SubsliceSpecTuple) -> None:
        with self._mu:
            self._op("destroy_subslice")
            if tup not in self._state.subslices:
                raise SubsliceNotFoundError(f"no live sub-slice {tup.canonical_name()}")
            del self._state.subslices[tup]

    def list_subslices(self) -> List[LiveSubslice]:
        with self._mu:
            return [LiveSubslice(spec_tuple=t, live=l)
                    for t, l in sorted(self._state.subslices.items(),
                                       key=lambda kv: kv[0].canonical_name())]

    # -- sharing knobs ------------------------------------------------------

    def set_timeslice(self, chip_uuid: str, interval: TimesliceInterval) -> None:
        with self._mu:
            self._op("set_timeslice")
            self._assert_chip(chip_uuid)
            self._state.timeslice[chip_uuid] = interval

    def set_exclusive_mode(self, chip_uuid: str, exclusive: bool) -> None:
        with self._mu:
            self._op("set_exclusive_mode")
            self._assert_chip(chip_uuid)
            self._state.exclusive[chip_uuid] = exclusive

    def get_timeslice(self, chip_uuid: str) -> TimesliceInterval:
        with self._mu:
            return self._state.timeslice.get(chip_uuid, TimesliceInterval.DEFAULT)

    def get_exclusive_mode(self, chip_uuid: str) -> bool:
        with self._mu:
            return self._state.exclusive.get(chip_uuid, False)

    # -- multi-process share ledger + modeled contention --------------------

    def allocate_multiprocess_share(self, chip_uuid: str, owner: str,
                                    max_clients: int,
                                    hbm_limit_percent: int) -> MultiProcessShare:
        with self._mu:
            self._op("allocate_multiprocess_share")
            chip = self._assert_chip(chip_uuid)
            if self._state.mp_seats.get(chip_uuid):
                raise SharingExhaustedError(
                    f"chip {chip_uuid} carries per-claim client seats; a "
                    f"whole-chip share cannot coexist with them")
            existing = self._state.mp_shares.get(chip_uuid)
            if existing is not None:
                if existing.owner == owner:
                    return existing      # idempotent re-prepare
                raise SharingExhaustedError(
                    f"chip {chip_uuid} already shared by claim "
                    f"{existing.owner}")
            if max_clients * hbm_limit_percent > 100:
                raise SharingExhaustedError(
                    f"over-subscribed: {max_clients} clients x "
                    f"{hbm_limit_percent}% HBM exceeds the chip")
            share = MultiProcessShare(
                chip_uuid=chip_uuid, owner=owner, max_clients=max_clients,
                hbm_limit_percent=hbm_limit_percent,
                client_hbm_bytes=chip.hbm_bytes * hbm_limit_percent // 100)
            self._state.mp_shares[chip_uuid] = share
            self._state.mp_clients[chip_uuid] = {}
            return share

    def release_multiprocess_share(self, chip_uuid: str,
                                   owner: Optional[str] = None) -> None:
        with self._mu:
            self._op("release_multiprocess_share")
            share = self._state.mp_shares.get(chip_uuid)
            if share is None:
                return
            if owner is not None and share.owner != owner:
                raise TpuLibError(
                    f"share on {chip_uuid} owned by {share.owner}, "
                    f"not {owner}")
            del self._state.mp_shares[chip_uuid]
            self._state.mp_clients.pop(chip_uuid, None)

    def get_multiprocess_share(self, chip_uuid: str) -> Optional[MultiProcessShare]:
        with self._mu:
            return self._state.mp_shares.get(chip_uuid)

    # -- multi-owner client seats (claim-per-request serving) ---------------

    def attach_multiprocess_seat(self, chip_uuid: str, owner: str,
                                 seat: int,
                                 hbm_limit_percent: int) -> MultiProcessShare:
        with self._mu:
            self._op("attach_multiprocess_seat")
            chip = self._assert_chip(chip_uuid)
            if not (0 <= seat < SEAT_COUNT):
                raise TpuLibError(f"seat {seat} outside [0, {SEAT_COUNT})")
            if self._state.mp_shares.get(chip_uuid) is not None:
                raise SharingExhaustedError(
                    f"chip {chip_uuid} carries a whole-chip share; seats "
                    f"cannot coexist with it")
            seats = self._state.mp_seats.setdefault(chip_uuid, {})
            existing = seats.get(seat)
            if existing is not None:
                if existing.owner == owner:
                    return existing      # idempotent re-prepare
                raise SharingExhaustedError(
                    f"seat {seat} on chip {chip_uuid} held by claim "
                    f"{existing.owner}")
            total_pct = sum(s.hbm_limit_percent for s in seats.values())
            if total_pct + hbm_limit_percent > 100:
                raise SharingExhaustedError(
                    f"chip {chip_uuid}: aggregate seat HBM "
                    f"{total_pct + hbm_limit_percent}% exceeds the chip")
            core = seat_core(seat, chip.cores)
            for tup in self._state.subslices:
                if tup.parent_index != chip.index:
                    continue
                try:
                    ocores, _ = parse_profile_id(tup.profile_id)
                except ValueError as e:
                    raise TpuLibError(str(e)) from e
                if tup.placement_start <= core < tup.placement_start + ocores:
                    # TRANSIENT, not SharingExhausted: the partition will
                    # be reclaimed (and the republish hides this seat
                    # meanwhile) — a re-placed claim succeeds without any
                    # config change
                    raise TpuLibError(
                        f"core {core} of chip {chip.index} is partitioned "
                        f"({tup.canonical_name()}); seat {seat} cannot "
                        f"attach")
            share = MultiProcessShare(
                chip_uuid=chip_uuid, owner=owner, max_clients=1,
                hbm_limit_percent=hbm_limit_percent,
                client_hbm_bytes=chip.hbm_bytes * hbm_limit_percent // 100,
                seat=seat)
            seats[seat] = share
            return share

    def detach_multiprocess_seat(self, chip_uuid: str,
                                 owner: Optional[str] = None,
                                 seat: Optional[int] = None) -> None:
        with self._mu:
            self._op("detach_multiprocess_seat")
            seats = self._state.mp_seats.get(chip_uuid, {})
            victims = [k for k, s in seats.items()
                       if (owner is None or s.owner == owner)
                       and (seat is None or k == seat)]
            for k in victims:
                gone = seats.pop(k)
                owners = self._state.mp_client_owner.get(chip_uuid, {})
                for cid in [c for c, o in owners.items()
                            if o == gone.owner]:
                    owners.pop(cid, None)
                    self._state.mp_clients.get(chip_uuid, {}).pop(cid, None)
            if not seats:
                self._state.mp_seats.pop(chip_uuid, None)

    def list_multiprocess_seats(self, chip_uuid: str
                                ) -> Dict[int, MultiProcessShare]:
        with self._mu:
            return dict(self._state.mp_seats.get(chip_uuid, {}))

    # what the runtime (libtpu) does with the grant — modeled so tests
    # can prove the limits bind (the reference's MPS daemon enforcement,
    # sharing.go:151-436):

    def connect_multiprocess_client(self, chip_uuid: str,
                                    owner: Optional[str] = None) -> int:
        """A workload process attaches to the shared chip. Fails once
        max_clients are connected. With ``owner``, the process attaches
        AS that claim's seat client (SharedChipServing: one client per
        seat, budgeted by the seat's share)."""
        with self._mu:
            if owner is not None:
                seats = self._state.mp_seats.get(chip_uuid, {})
                share = next((s for s in seats.values()
                              if s.owner == owner), None)
                if share is None:
                    raise TpuLibError(
                        f"claim {owner} holds no seat on {chip_uuid}")
                owners = self._state.mp_client_owner.setdefault(
                    chip_uuid, {})
                if owner in owners.values():
                    raise SharingExhaustedError(
                        f"seat of claim {owner} on {chip_uuid} already "
                        f"has its client connected")
                cid = self._state.mp_next_client
                self._state.mp_next_client += 1
                self._state.mp_clients.setdefault(chip_uuid, {})[cid] = 0
                owners[cid] = owner
                return cid
            share = self._state.mp_shares.get(chip_uuid)
            if share is None:
                raise TpuLibError(f"chip {chip_uuid} is not shared")
            clients = self._state.mp_clients[chip_uuid]
            if len(clients) >= share.max_clients:
                raise SharingExhaustedError(
                    f"chip {chip_uuid}: {share.max_clients} clients "
                    f"already connected")
            cid = self._state.mp_next_client
            self._state.mp_next_client += 1
            clients[cid] = 0
            return cid

    def disconnect_multiprocess_client(self, chip_uuid: str, cid: int) -> None:
        with self._mu:
            self._state.mp_clients.get(chip_uuid, {}).pop(cid, None)
            self._state.mp_client_owner.get(chip_uuid, {}).pop(cid, None)

    def _client_budget_locked(self, chip_uuid: str, cid: int) -> Optional[int]:
        owner = self._state.mp_client_owner.get(chip_uuid, {}).get(cid)
        if owner is not None:
            seats = self._state.mp_seats.get(chip_uuid, {})
            share = next((s for s in seats.values()
                          if s.owner == owner), None)
            return None if share is None else share.client_hbm_bytes
        share = self._state.mp_shares.get(chip_uuid)
        return None if share is None else share.client_hbm_bytes

    def client_allocate_hbm(self, chip_uuid: str, cid: int, nbytes: int) -> None:
        """Model a client's HBM allocation: bounded by its per-client
        budget AND the physical chip (so even conspiring clients cannot
        exceed the hardware)."""
        with self._mu:
            budget = self._client_budget_locked(chip_uuid, cid)
            clients = self._state.mp_clients.get(chip_uuid, {})
            if budget is None or cid not in clients:
                raise TpuLibError(f"client {cid} not connected to {chip_uuid}")
            chip = self._assert_chip(chip_uuid)
            if clients[cid] + nbytes > budget:
                raise SharingExhaustedError(
                    f"client {cid} exceeds its "
                    f"{budget}-byte HBM budget")
            if sum(clients.values()) + nbytes > chip.hbm_bytes:
                raise SharingExhaustedError(
                    f"chip {chip_uuid} HBM exhausted")
            clients[cid] += nbytes

    def _assert_chip(self, chip_uuid: str) -> ChipInfo:
        for c in self._chips:
            if c.uuid == chip_uuid:
                return c
        raise TpuLibError(f"no chip with uuid {chip_uuid}")

    # -- health -------------------------------------------------------------

    def subscribe_health(self, callback: Callable[[HealthEvent], None]) -> Callable[[], None]:
        return self._health.subscribe(callback)

    # -- vfio ---------------------------------------------------------------

    def current_driver(self, pci_address: str) -> Optional[str]:
        with self._mu:
            if pci_address in self._state.vfio_bound:
                return "vfio-pci"
            if any(c.pci_address == pci_address for c in self._chips):
                return "tpu"
            return None

    def bind_to_vfio(self, pci_address: str) -> str:
        with self._mu:
            self._op("bind_to_vfio")
            if not any(c.pci_address == pci_address for c in self._chips):
                raise TpuLibError(f"no chip at {pci_address}")
            if pci_address in self._state.in_use:
                raise TpuLibError(f"device {pci_address} busy")
            if pci_address in self._state.vfio_bound:
                return self._state.vfio_bound[pci_address]
            group = f"/dev/vfio/{self._state.next_vfio_group}"
            self._state.next_vfio_group += 1
            self._state.vfio_bound[pci_address] = group
            return group

    def unbind_from_vfio(self, pci_address: str) -> None:
        with self._mu:
            self._op("unbind_from_vfio")
            if pci_address not in self._state.vfio_bound:
                raise TpuLibError(f"device {pci_address} not vfio-bound")
            del self._state.vfio_bound[pci_address]

    def device_in_use(self, pci_address: str) -> bool:
        with self._mu:
            return pci_address in self._state.in_use

    def set_device_in_use(self, pci_address: str, in_use: bool) -> None:
        with self._mu:
            if in_use:
                self._state.in_use.add(pci_address)
            else:
                self._state.in_use.discard(pci_address)

    # -- versions -----------------------------------------------------------

    def driver_version(self) -> str:
        return self._cfg.driver_version
