"""Validating admission webhook for ResourceClaims/ResourceClaimTemplates.

Reference analog: cmd/webhook/{main.go:112-260, resource.go:33-140} — an
optional webhook that strict-decodes the opaque device configs of *both*
driver names in incoming ResourceClaim[Template]s and runs
Normalize()+Validate(), so typos fail at admission time instead of at
Prepare time on the node. When disabled, the Helm chart's
ValidatingAdmissionPolicy provides a coarser fallback.

``review()`` is the pure core (AdmissionReview in → AdmissionReview out);
``WebhookServer`` wraps it in HTTPS.
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from tpu_dra_driver import COMPUTE_DOMAIN_DRIVER_NAME, DRIVER_NAME
from tpu_dra_driver.api.decoder import STRICT_DECODER, DecodeError
from tpu_dra_driver.api.configs import ValidationError

log = logging.getLogger(__name__)

OUR_DRIVERS = (DRIVER_NAME, COMPUTE_DOMAIN_DRIVER_NAME)


def _validate_device_config(cfg: Dict, where: str) -> List[str]:
    errors = []
    opaque = cfg.get("opaque")
    if not opaque:
        return errors
    if opaque.get("driver") not in OUR_DRIVERS:
        return errors  # not ours to validate
    params = opaque.get("parameters")
    if params is None:
        return [f"{where}: opaque config missing parameters"]
    try:
        STRICT_DECODER.decode_validated(params)
    except (DecodeError, ValidationError) as e:
        errors.append(f"{where}: {e}")
    return errors


def validate_claim_spec(spec: Dict, where: str) -> List[str]:
    errors = []
    for i, cfg in enumerate((spec.get("devices") or {}).get("config") or []):
        errors.extend(_validate_device_config(cfg, f"{where}.devices.config[{i}]"))
    return errors


def validate_object(obj: Dict) -> List[str]:
    kind = obj.get("kind", "")
    if kind == "ResourceClaim":
        return validate_claim_spec(obj.get("spec") or {}, "spec")
    if kind == "ResourceClaimTemplate":
        return validate_claim_spec(
            ((obj.get("spec") or {}).get("spec") or {}), "spec.spec")
    return []


def review(admission_review: Dict) -> Dict:
    """AdmissionReview(v1) request → response; allowed unless a strict
    decode/validation of one of our opaque configs fails."""
    request = admission_review.get("request") or {}
    uid = request.get("uid", "")
    obj = request.get("object") or {}
    errors = validate_object(obj)
    response: Dict = {"uid": uid, "allowed": not errors}
    if errors:
        response["status"] = {
            "code": 422,
            "message": "; ".join(errors),
        }
        log.info("denied %s %s: %s", obj.get("kind"),
                 (obj.get("metadata") or {}).get("name"), errors)
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": response,
    }


class _Handler(BaseHTTPRequestHandler):
    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length)
        try:
            try:
                incoming = json.loads(body)
            except ValueError:
                self.send_response(400)
                self.end_headers()
                return
            outgoing = review(incoming)
            payload = json.dumps(outgoing).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except Exception:
            log.exception("admission review failed")
            self.send_response(500)
            self.end_headers()

    def log_message(self, fmt, *args):
        log.debug("webhook http: " + fmt, *args)


class WebhookServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 8443,
                 cert_file: Optional[str] = None,
                 key_file: Optional[str] = None):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        if cert_file and key_file:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(cert_file, key_file)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="webhook")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=2.0)
